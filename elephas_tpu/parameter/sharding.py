"""Sharded parameter plane: partition the weight list across N servers.

One parameter server caps async scaling at one process's RPC
throughput — every worker's pull and push funnels through it. The
classic fix (Li et al., *Scaling Distributed Machine Learning with the
Parameter Server*, OSDI 2014) shards the parameters across server
instances so pulls and pushes fan out and the wire work parallelizes.

Three pieces:

- :class:`ShardPlan` — a deterministic partition of the flat weight
  list over ``num_shards`` bins by greedy byte-size bin-packing
  (largest tensor first onto the lightest bin), with ``split``/``merge``
  to scatter a flat array list into per-shard sublists and gather them
  back in original order. The plan is a pure function of the weight
  shapes and the shard count, so every client and server derives the
  SAME plan independently — nothing about the partition crosses the
  wire.
- :class:`ShardedServerGroup` — N ordinary parameter servers (any
  registered transport) on consecutive ports ``port .. port+N-1``, each
  holding its shard's weights. Per-shard ``snapshot``/``restore``/
  ``restart_shard`` keep ``ps_auto_restart`` working: a dead shard is
  rebuilt from ITS snapshot while the surviving shards keep serving.
- :class:`ShardedParameterClient` — fans ``get_parameters`` /
  ``update_parameters`` out over per-shard clients in parallel threads
  and reassembles results in plan order. Works over both HTTP and
  socket transports (each sub-client keeps its own persistent
  connection, retry loop, and metrics).

Consistency/staleness semantics and the operator-facing overview live
ONCE in :mod:`elephas_tpu.parameter.server`'s module docstring (the
"Sharding the parameter plane" section of the parameter-servers guide)
— edit them there, not here.

Exposed as ``ps_shards=N`` on :class:`~elephas_tpu.tpu_model.TPUModel`
and via :func:`~elephas_tpu.parameter.factory.create_sharded_server` /
:func:`~elephas_tpu.parameter.factory.create_sharded_client`.
"""
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .client import BaseParameterClient

__all__ = ["ShardPlan", "ShardedServerGroup", "ShardedParameterClient"]


def _nbytes(shape, dtype=np.float32) -> int:
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


class ShardPlan:
    """A deterministic partition of ``len(sizes)`` tensors over
    ``num_shards`` bins, balanced by byte size.

    Greedy bin-packing: tensors are visited largest-first (ties broken
    by index, so the plan is total-order deterministic) and each goes
    to the currently lightest bin (ties broken by bin index). Within a
    bin, tensors keep their original relative order — reassembly is a
    stable scatter/gather, not a sort.
    """

    def __init__(self, assignments: Sequence[Sequence[int]],
                 sizes: Sequence[int]):
        self.assignments: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(a) for a in assignments)
        self.sizes = tuple(int(s) for s in sizes)
        seen = sorted(i for part in self.assignments for i in part)
        if seen != list(range(len(self.sizes))):
            raise ValueError("assignments must cover every tensor index "
                             "exactly once")

    @property
    def num_shards(self) -> int:
        return len(self.assignments)

    @property
    def shard_bytes(self) -> Tuple[int, ...]:
        """Total payload bytes per shard (the balance the packing
        optimizes)."""
        return tuple(sum(self.sizes[i] for i in part)
                     for part in self.assignments)

    @classmethod
    def plan(cls, weights: Sequence, num_shards: int) -> "ShardPlan":
        """Plan from a list of arrays (or shape tuples, float32 assumed).

        ``num_shards`` may exceed the tensor count; the excess bins are
        empty (their servers hold zero weights and answer every pull
        with an empty list — harmless, but a waste of ports).
        """
        num_shards = int(num_shards)
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        sizes = []
        for w in weights:
            if hasattr(w, "nbytes"):
                sizes.append(int(np.asarray(w).nbytes))
            else:
                sizes.append(_nbytes(tuple(w)))
        loads = [0] * num_shards
        bins: List[List[int]] = [[] for _ in range(num_shards)]
        # largest first, ties by index — deterministic across processes
        for idx in sorted(range(len(sizes)),
                          key=lambda i: (-sizes[i], i)):
            b = min(range(num_shards), key=lambda j: (loads[j], j))
            loads[b] += sizes[idx]
            bins[b].append(idx)
        return cls([sorted(b) for b in bins], sizes)

    def split(self, arrays: Sequence, group: int = 1) -> List[List]:
        """Scatter a flat list into per-shard sublists (plan order).

        ``group`` is the per-tensor stride in ``arrays``: 1 for plain
        weight/delta lists, 2 for ``KIND_DELTA_Q8`` frames where tensor
        ``i`` owns the interleaved ``(data, scale)`` pair at
        ``arrays[2i:2i+2]``.
        """
        if len(arrays) != group * len(self.sizes):
            raise ValueError(
                f"expected {group * len(self.sizes)} arrays "
                f"(group={group}), got {len(arrays)}")
        return [[arrays[group * i + k] for i in part for k in range(group)]
                for part in self.assignments]

    def merge(self, parts: Sequence[Sequence], group: int = 1) -> List:
        """Gather per-shard sublists back into the flat original order
        (inverse of :meth:`split`)."""
        out: List = [None] * (group * len(self.sizes))
        for part, arrays in zip(self.assignments, parts):
            if len(arrays) != group * len(part):
                raise ValueError(
                    f"shard returned {len(arrays)} arrays, plan expects "
                    f"{group * len(part)}")
            for j, i in enumerate(part):
                for k in range(group):
                    out[group * i + k] = arrays[group * j + k]
        return out

    def shard_model(self, model: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Per-shard ``model_to_dict``-style payloads: each carries its
        shard's weight sublist (the architecture config rides along on
        every shard — it is small and keeps the save/parity surface of
        :class:`~elephas_tpu.parameter.server.BaseParameterServer`
        intact)."""
        parts = self.split(list(model["weights"]))
        return [{"model": model.get("model"), "weights": part}
                for part in parts]


class _Fanout:
    """Run one callable per shard on a PERSISTENT thread pool; collect
    results in shard order; re-raise the first failure AFTER every call
    has finished (a straggler RPC must not be abandoned mid-frame on a
    persistent connection).

    The pool lives as long as its owner: batch-frequency workers fan
    out twice per round (pull + push) plus health probes, and spawning
    N fresh threads per RPC is GIL-held overhead repaid on every
    round."""

    def __init__(self, size: int):
        self._pool = ThreadPoolExecutor(
            max_workers=size, thread_name_prefix="elephas-tpu-ps-shard")

    def run(self, fns: Sequence) -> List:
        if len(fns) == 1:           # no pool tax for the 1-shard case
            return [fns[0]()]
        futures = [self._pool.submit(fn) for fn in fns]
        results: List = [None] * len(fns)
        first: Optional[BaseException] = None
        for i, fut in enumerate(futures):  # waits for EVERY call
            try:
                results[i] = fut.result()
            except BaseException as err:  # noqa: BLE001 — re-raised below
                first = first or err
        if first is not None:
            raise first
        return results

    def close(self):
        # no wait: close() must not block behind a stuck in-flight RPC
        self._pool.shutdown(wait=False)


class ShardedParameterClient(BaseParameterClient):
    """Client for a :class:`ShardedServerGroup`: one sub-client per
    shard, RPCs fanned out on parallel threads, results reassembled in
    plan order.

    Each sub-client keeps its own transport state (persistent socket,
    retry/backoff loop, latency metrics), so a slow or restarting shard
    costs only its own lane. ``compression`` lives HERE, not on the
    sub-clients: a compressed push quantizes the full delta once and
    ships each shard its slice of the quantized frame.
    """

    client_type = "sharded"

    def __init__(self, clients: Sequence[BaseParameterClient],
                 plan: ShardPlan, compression: Optional[str] = None):
        if len(clients) != plan.num_shards:
            raise ValueError(
                f"{len(clients)} clients for a {plan.num_shards}-shard plan")
        self.clients = list(clients)
        self.plan = plan
        self.compression = self._check_compression(compression)
        self._fanout = _Fanout(len(self.clients))

    def clone(self) -> "ShardedParameterClient":
        return ShardedParameterClient([c.clone() for c in self.clients],
                                      self.plan,
                                      compression=self.compression)

    def get_parameters(self) -> List[np.ndarray]:
        parts = self._fanout.run([c.get_parameters for c in self.clients])
        return self.plan.merge(parts)

    def get_version(self):
        """Per-shard weight versions as a tuple (plan order), fanned out
        in parallel like every other RPC. Each shard versions its own
        slice independently, so the tuple IS the plane's version token:
        a subscriber compares tuples for inequality (any shard moved =
        the assembled weights changed) and sums them when it needs one
        number for a gauge."""
        return tuple(int(v) for v in self._fanout.run(
            [c.get_version for c in self.clients]))

    def get_parameters_versioned(self):
        """``(versions, weights)``: per-shard versioned pulls fanned
        out over the plan, reassembled in plan order. Consistency is
        per shard, like :meth:`get_parameters` — a concurrent push can
        land between shard reads (the documented sharded-PS trade);
        the racing shard's version shows up changed on the next poll,
        so a subscriber simply converges one pull later."""
        pairs = self._fanout.run([c.get_parameters_versioned
                                  for c in self.clients])
        versions = tuple(int(v) for v, _ in pairs)
        return versions, self.plan.merge([w for _, w in pairs])

    def push_frame(self, arrays: List[np.ndarray], kind: int):
        """Fan one update out to every shard.

        There is NO cross-shard transaction: if one shard exhausts its
        sub-client retries after siblings already applied, the update
        lands torn (some tensors updated, the failed shard's slice
        lost). For asynchronous SGD that is one partial gradient — the
        same class of perturbation as a lost delta, which the training
        mode already tolerates — but it is observable: a partial
        failure emits a ``ps.sharded_push_torn`` event before the error
        propagates (and the failed shard's ``num_updates`` lags, which
        the group-min progress signal surfaces)."""
        from ..obs.events import emit as emit_event
        from ..utils.tensor_codec import KIND_DELTA_Q8

        group = 2 if kind == KIND_DELTA_Q8 else 1
        parts = self.plan.split(list(arrays), group=group)
        applied = [False] * len(self.clients)

        def push_one(i, c, p):
            def call():
                c.push_frame(p, kind)
                applied[i] = True
            return call

        try:
            self._fanout.run([push_one(i, c, p) for i, (c, p)
                              in enumerate(zip(self.clients, parts))])
        except BaseException:
            if any(applied):
                emit_event("ps.sharded_push_torn",
                           shards_applied=sum(applied),
                           shards_total=len(applied))
            raise

    def health_check(self) -> bool:
        return all(self._fanout.run([c.health_check
                                     for c in self.clients]))

    def close(self):
        for c in self.clients:
            c.close()
        self._fanout.close()


class ShardedServerGroup:
    """N parameter servers (one transport) on ports ``port..port+N-1``,
    each holding one shard of the weight list.

    Presents the single-server admin surface (``start``/``stop``/
    ``snapshot``/``restore``/``num_updates``) plus the per-shard
    operations ``ps_auto_restart`` supervision needs: a dead shard is
    rebuilt from its own snapshot on its own port
    (:meth:`restart_shard`) while the others keep serving.
    """

    def __init__(self, transport, model: Dict[str, Any], port: int,
                 mode: str, num_shards: int, **kwargs):
        self.transport = transport
        self.port = int(port)
        self.mode = mode
        self.kwargs = dict(kwargs)
        self.plan = ShardPlan.plan(model["weights"], num_shards)
        self._shard_models = self.plan.shard_model(model)
        self.servers = [
            transport.create_server(self._shard_models[i], self.port + i,
                                    mode, shard=i, **self.kwargs)
            for i in range(self.plan.num_shards)]

    @property
    def num_shards(self) -> int:
        return self.plan.num_shards

    @property
    def num_updates(self) -> int:
        """Progress signal: the MINIMUM applied-update count across
        shards — every worker push touches every shard, so the slowest
        shard's counter is the number of fully-landed updates."""
        return min(s.num_updates for s in self.servers)

    def start(self):
        started = []
        try:
            for s in self.servers:
                s.start()
                started.append(s)
        except BaseException:
            for s in started:      # no half-started group left behind
                try:
                    s.stop()
                except Exception:
                    pass
            raise

    def stop(self):
        first: Optional[BaseException] = None
        for s in self.servers:
            try:
                s.stop()
            except Exception as err:  # stop every shard before raising
                first = first or err
        if first is not None:
            raise first

    def get_weights(self) -> List[np.ndarray]:
        """The full reassembled weight list (driver-side convenience —
        remote callers use :class:`ShardedParameterClient`)."""
        return self.plan.merge([s.get_weights() for s in self.servers])

    def snapshot(self) -> Dict[str, Any]:
        return {"shards": [s.snapshot() for s in self.servers]}

    def restore(self, snapshot: Dict[str, Any]):
        shards = snapshot["shards"]
        if len(shards) != len(self.servers):
            raise ValueError(
                f"snapshot has {len(shards)} shards, group has "
                f"{len(self.servers)}")
        for s, snap in zip(self.servers, shards):
            s.restore(snap)

    def snapshot_shard(self, i: int) -> Dict[str, Any]:
        return self.servers[i].snapshot()

    def restart_shard(self, i: int, snapshot: Dict[str, Any]):
        """Kill→restart recovery for ONE shard: stop whatever is left of
        the old server, rebuild it from ``snapshot`` on the same port,
        start it. Workers reconnect through their sub-clients' retry
        path; the restored idempotency window keeps in-flight resends
        deduplicated."""
        try:
            self.servers[i].stop()
        except Exception:
            pass  # already dead — the port is what matters
        server = self.transport.create_server(
            {"model": self._shard_models[i].get("model"),
             "weights": snapshot["weights"]},
            self.port + i, self.mode, shard=i, **self.kwargs)
        server.restore(snapshot)
        server.start()
        self.servers[i] = server
        return server
