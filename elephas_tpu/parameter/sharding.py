"""Sharded parameter plane: partition the weight list across N servers.

One parameter server caps async scaling at one process's RPC
throughput — every worker's pull and push funnels through it. The
classic fix (Li et al., *Scaling Distributed Machine Learning with the
Parameter Server*, OSDI 2014) shards the parameters across server
instances so pulls and pushes fan out and the wire work parallelizes.

Three pieces:

- :class:`ShardPlan` — a deterministic partition of the flat weight
  list over ``num_shards`` bins by greedy byte-size bin-packing
  (largest tensor first onto the lightest bin), with ``split``/``merge``
  to scatter a flat array list into per-shard sublists and gather them
  back in original order. The plan is a pure function of the weight
  shapes and the shard count, so every client and server derives the
  SAME plan independently — nothing about the partition crosses the
  wire.
- :class:`ShardedServerGroup` — N ordinary parameter servers (any
  registered transport) on consecutive ports ``port .. port+N-1``, each
  holding its shard's weights. Per-shard ``snapshot``/``restore``/
  ``restart_shard`` keep ``ps_auto_restart`` working: a dead shard is
  rebuilt from ITS snapshot while the surviving shards keep serving.
- :class:`ShardedParameterClient` — fans ``get_parameters`` /
  ``update_parameters`` out over per-shard clients in parallel threads
  and reassembles results in plan order. Works over both HTTP and
  socket transports (each sub-client keeps its own persistent
  connection, retry loop, and metrics).

Consistency/staleness semantics and the operator-facing overview live
ONCE in :mod:`elephas_tpu.parameter.server`'s module docstring (the
"Sharding the parameter plane" section of the parameter-servers guide)
— edit them there, not here.

Exposed as ``ps_shards=N`` on :class:`~elephas_tpu.tpu_model.TPUModel`
and via :func:`~elephas_tpu.parameter.factory.create_sharded_server` /
:func:`~elephas_tpu.parameter.factory.create_sharded_client`.
"""
import urllib.error
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .client import _TRANSIENT, BaseParameterClient, UnknownTxnError

__all__ = ["ShardPlan", "ShardedServerGroup", "ShardedParameterClient",
           "TornPushError", "CommitAbortedError",
           "GenerationMismatchError"]


class TornPushError(ConnectionError):
    """A sharded push landed on some shards but exhausted retries on
    another — the plane is TORN (the failed shard's slice lost). Typed
    so callers can distinguish torn from never-applied: a plain
    :class:`ConnectionError` from the sharded client means NO shard
    applied anything. ``per_shard`` holds one outcome string per shard
    in plan order (``"applied"`` / ``"failed: ..."``)."""

    def __init__(self, message: str, per_shard: Sequence[str]):
        super().__init__(message)
        self.per_shard = list(per_shard)


class CommitAbortedError(ConnectionError):
    """A two-phase push failed TRANSIENTLY in the PREPARE phase and was
    aborted on every shard — nothing was applied anywhere (the
    atomic-commit guarantee). Safe to retry the whole push. Permanent
    rejections (mis-shaped delta: ``ValueError`` from the socket
    transport, HTTP 4xx) also abort every shard but propagate typed —
    retrying them can never succeed."""


class GenerationMismatchError(RuntimeError):
    """A generation-coherent pull could not assemble a consistent
    weight set: the shards kept disagreeing on (generation, digest)
    past the bounded re-pull budget — the plane is mid-push, torn, or a
    shard restarted lossily. ``versions`` (the per-shard version tuple
    observed — the token a subscriber vetoes) and ``generations`` ride
    along for the veto and the event log."""

    def __init__(self, generations, versions):
        super().__init__(
            f"shards disagree on generation after re-pulls: "
            f"{generations}")
        self.generations = tuple(generations)
        self.versions = tuple(versions)


def _nbytes(shape, dtype=np.float32) -> int:
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


class ShardPlan:
    """A deterministic partition of ``len(sizes)`` tensors over
    ``num_shards`` bins, balanced by byte size.

    Greedy bin-packing: tensors are visited largest-first (ties broken
    by index, so the plan is total-order deterministic) and each goes
    to the currently lightest bin (ties broken by bin index). Within a
    bin, tensors keep their original relative order — reassembly is a
    stable scatter/gather, not a sort.
    """

    def __init__(self, assignments: Sequence[Sequence[int]],
                 sizes: Sequence[int]):
        self.assignments: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(a) for a in assignments)
        self.sizes = tuple(int(s) for s in sizes)
        seen = sorted(i for part in self.assignments for i in part)
        if seen != list(range(len(self.sizes))):
            raise ValueError("assignments must cover every tensor index "
                             "exactly once")

    @property
    def num_shards(self) -> int:
        return len(self.assignments)

    @property
    def shard_bytes(self) -> Tuple[int, ...]:
        """Total payload bytes per shard (the balance the packing
        optimizes)."""
        return tuple(sum(self.sizes[i] for i in part)
                     for part in self.assignments)

    @classmethod
    def plan(cls, weights: Sequence, num_shards: int) -> "ShardPlan":
        """Plan from a list of arrays (or shape tuples, float32 assumed).

        ``num_shards`` may exceed the tensor count; the excess bins are
        empty (their servers hold zero weights and answer every pull
        with an empty list — harmless, but a waste of ports).
        """
        num_shards = int(num_shards)
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        sizes = []
        for w in weights:
            if hasattr(w, "nbytes"):
                sizes.append(int(np.asarray(w).nbytes))
            else:
                sizes.append(_nbytes(tuple(w)))
        loads = [0] * num_shards
        bins: List[List[int]] = [[] for _ in range(num_shards)]
        # largest first, ties by index — deterministic across processes
        for idx in sorted(range(len(sizes)),
                          key=lambda i: (-sizes[i], i)):
            b = min(range(num_shards), key=lambda j: (loads[j], j))
            loads[b] += sizes[idx]
            bins[b].append(idx)
        return cls([sorted(b) for b in bins], sizes)

    def split(self, arrays: Sequence, group: int = 1) -> List[List]:
        """Scatter a flat list into per-shard sublists (plan order).

        ``group`` is the per-tensor stride in ``arrays``: 1 for plain
        weight/delta lists, 2 for ``KIND_DELTA_Q8`` frames where tensor
        ``i`` owns the interleaved ``(data, scale)`` pair at
        ``arrays[2i:2i+2]``.
        """
        if len(arrays) != group * len(self.sizes):
            raise ValueError(
                f"expected {group * len(self.sizes)} arrays "
                f"(group={group}), got {len(arrays)}")
        return [[arrays[group * i + k] for i in part for k in range(group)]
                for part in self.assignments]

    def merge(self, parts: Sequence[Sequence], group: int = 1) -> List:
        """Gather per-shard sublists back into the flat original order
        (inverse of :meth:`split`)."""
        out: List = [None] * (group * len(self.sizes))
        for part, arrays in zip(self.assignments, parts):
            if len(arrays) != group * len(part):
                raise ValueError(
                    f"shard returned {len(arrays)} arrays, plan expects "
                    f"{group * len(part)}")
            for j, i in enumerate(part):
                for k in range(group):
                    out[group * i + k] = arrays[group * j + k]
        return out

    def shard_model(self, model: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Per-shard ``model_to_dict``-style payloads: each carries its
        shard's weight sublist (the architecture config rides along on
        every shard — it is small and keeps the save/parity surface of
        :class:`~elephas_tpu.parameter.server.BaseParameterServer`
        intact)."""
        parts = self.split(list(model["weights"]))
        return [{"model": model.get("model"), "weights": part}
                for part in parts]


class _Fanout:
    """Run one callable per shard on a PERSISTENT thread pool; collect
    results in shard order; re-raise the first failure AFTER every call
    has finished (a straggler RPC must not be abandoned mid-frame on a
    persistent connection).

    The pool lives as long as its owner: batch-frequency workers fan
    out twice per round (pull + push) plus health probes, and spawning
    N fresh threads per RPC is GIL-held overhead repaid on every
    round."""

    def __init__(self, size: int):
        self._pool = ThreadPoolExecutor(
            max_workers=size, thread_name_prefix="elephas-tpu-ps-shard")

    def run(self, fns: Sequence) -> List:
        if len(fns) == 1:           # no pool tax for the 1-shard case
            return [fns[0]()]
        futures = [self._pool.submit(fn) for fn in fns]
        results: List = [None] * len(fns)
        first: Optional[BaseException] = None
        for i, fut in enumerate(futures):  # waits for EVERY call
            try:
                results[i] = fut.result()
            except BaseException as err:  # noqa: BLE001 — re-raised below
                first = first or err
        if first is not None:
            raise first
        return results

    def close(self):
        # no wait: close() must not block behind a stuck in-flight RPC
        self._pool.shutdown(wait=False)


class ShardedParameterClient(BaseParameterClient):
    """Client for a :class:`ShardedServerGroup`: one sub-client per
    shard, RPCs fanned out on parallel threads, results reassembled in
    plan order.

    Each sub-client keeps its own transport state (persistent socket,
    retry/backoff loop, latency metrics), so a slow or restarting shard
    costs only its own lane. ``compression`` lives HERE, not on the
    sub-clients: a compressed push quantizes the full delta once and
    ships each shard its slice of the quantized frame.
    """

    client_type = "sharded"

    #: re-pull rounds a generation-coherent pull spends converging on a
    #: consistent cut before raising :class:`GenerationMismatchError`
    MAX_COHERENCE_REPULLS = 4

    def __init__(self, clients: Sequence[BaseParameterClient],
                 plan: ShardPlan, compression: Optional[str] = None,
                 two_phase: bool = True):
        if len(clients) != plan.num_shards:
            raise ValueError(
                f"{len(clients)} clients for a {plan.num_shards}-shard plan")
        self.clients = list(clients)
        self.plan = plan
        self.compression = self._check_compression(compression)
        self.two_phase = bool(two_phase)
        # effective only when EVERY sub-client implements the prepare
        # extension: a transport (or in-memory double) without it falls
        # back to the legacy single-phase push rather than failing
        # half-prepared
        self._use_2pc = self.two_phase and all(
            type(c).prepare_frame is not BaseParameterClient.prepare_frame
            for c in self.clients)
        self._fanout = _Fanout(len(self.clients))
        from ..obs.metrics import default_registry

        self._m_commit_aborts = default_registry().counter(
            "ps_commit_aborts_total",
            "two-phase sharded pushes aborted in the prepare phase "
            "(nothing applied on any shard)").labels()

    def clone(self) -> "ShardedParameterClient":
        return ShardedParameterClient([c.clone() for c in self.clients],
                                      self.plan,
                                      compression=self.compression,
                                      two_phase=self.two_phase)

    def get_parameters(self) -> List[np.ndarray]:
        parts = self._fanout.run([c.get_parameters for c in self.clients])
        return self.plan.merge(parts)

    def get_version(self):
        """Per-shard weight versions as a tuple (plan order), fanned out
        in parallel like every other RPC. Each shard versions its own
        slice independently, so the tuple IS the plane's version token:
        a subscriber compares tuples for inequality (any shard moved =
        the assembled weights changed) and sums them when it needs one
        number for a gauge."""
        return tuple(int(v) for v in self._fanout.run(
            [c.get_version for c in self.clients]))

    def get_parameters_versioned(self):
        """``(versions, weights)``: per-shard versioned pulls fanned
        out over the plan, reassembled in plan order. Consistency is
        per shard, like :meth:`get_parameters` — a concurrent push can
        land between shard reads (the documented sharded-PS trade);
        the racing shard's version shows up changed on the next poll,
        so a subscriber simply converges one pull later."""
        pairs = self._fanout.run([c.get_parameters_versioned
                                  for c in self.clients])
        versions = tuple(int(v) for v, _ in pairs)
        return versions, self.plan.merge([w for _, w in pairs])

    def get_generation(self):
        """Per-shard ``(generation, digest)`` pairs as a tuple (plan
        order). Equal pairs across shards certify the same set of
        committed updates landed everywhere."""
        return tuple(self._fanout.run([c.get_generation
                                       for c in self.clients]))

    def get_parameters_generational(self):
        """A generation-COHERENT pull: every shard's
        ``((gen, digest), version, weights)`` triple is fetched in
        parallel, and shards whose generation pair disagrees with the
        most-advanced shard are re-pulled (they are mid-commit — a
        racing push lands between shard reads) up to
        :attr:`MAX_COHERENCE_REPULLS` rounds. Returns
        ``(generation_pair, version_tuple, merged_weights)`` once all
        shards agree; raises :class:`GenerationMismatchError` when they
        never converge (constant churn, a torn legacy push, or a
        lossily restarted shard) — the weight set that WOULD have been
        assembled is exactly the mixed-generation frankenstein state a
        subscriber must never stage."""
        triples = list(self._fanout.run(
            [c.get_parameters_generational for c in self.clients]))
        # N re-pull rounds = N+1 consistency checks: the LAST re-pull's
        # results are checked too, not fetched-and-discarded
        for attempt in range(self.MAX_COHERENCE_REPULLS + 1):
            pairs = [t[0] for t in triples]
            if len(set(pairs)) == 1:
                versions = tuple(int(t[1]) for t in triples)
                merged = self.plan.merge([t[2] for t in triples])
                return pairs[0], versions, merged
            if attempt == self.MAX_COHERENCE_REPULLS:
                break
            # re-pull the LAGGING shards (generation below the max —
            # their missing commit is in flight and lands shortly); a
            # same-count digest split means two different update sets,
            # so re-pull every minority shard and let the stream settle
            target = max(pairs)
            lagging = [i for i, p in enumerate(pairs) if p != target]
            repulled = self._fanout.run(
                [self.clients[i].get_parameters_generational
                 for i in lagging])
            for j, i in enumerate(lagging):
                triples[i] = repulled[j]
        raise GenerationMismatchError(
            generations=[t[0] for t in triples],
            versions=[int(t[1]) for t in triples])

    def push_frame(self, arrays: List[np.ndarray], kind: int,
                   update_id: Optional[str] = None):
        """Fan one update out to every shard.

        With ``two_phase=True`` (the default, when every sub-client
        speaks the prepare extension) the push is an ATOMIC cross-shard
        commit: every shard stages the delta first, any prepare failure
        aborts all shards — nothing applied anywhere; transient
        failures surface as the retryable :class:`CommitAbortedError`,
        permanent validation rejections propagate typed — and only
        then does the commit fan out.
        Returns the push's **generation id** (the max post-commit
        per-shard generation — monotonically increasing across
        committed pushes). A shard that failed over between prepare and
        commit answers the commit with unknown-txn; the coordinator
        re-prepares that shard's slice against the promoted standby and
        commits again, so a mid-push primary death costs a retry, not a
        torn plane.

        The legacy single-phase path (``two_phase=False``) keeps the
        documented no-cross-shard-transaction trade: a push whose
        retries exhaust on one shard after siblings applied lands torn
        — raised as :class:`TornPushError` carrying per-shard outcomes
        (a plain ``ConnectionError`` means nothing applied), plus the
        ``ps.sharded_push_torn`` event."""
        from ..utils.tensor_codec import KIND_DELTA_Q8

        group = 2 if kind == KIND_DELTA_Q8 else 1
        parts = self.plan.split(list(arrays), group=group)
        # ONE id per logical push, shared by every shard on BOTH paths:
        # the per-shard generation digests sum the ids of applied
        # updates, so per-shard minting would diverge the digests on the
        # very first push and the coherence check would veto every
        # generational pull forever
        update_id = update_id or uuid.uuid4().hex
        if self._use_2pc:
            return self._push_frame_2pc(parts, kind, update_id)
        return self._push_frame_legacy(parts, kind, update_id)

    def _push_frame_2pc(self, parts, kind: int, txn_id: str):
        from ..obs.events import emit as emit_event

        prepared = [False] * len(self.clients)

        def prepare_one(i, c, p):
            def call():
                c.prepare_frame(p, kind, txn_id)
                prepared[i] = True
            return call

        try:
            self._fanout.run([prepare_one(i, c, p) for i, (c, p)
                              in enumerate(zip(self.clients, parts))])
        except BaseException as err:
            # prepare failed somewhere: nothing has been APPLIED
            # anywhere — abort the shards that DID stage (best-effort;
            # a shard whose prepare failed has nothing to drop, and
            # retrying an abort against a dead shard would stall the
            # error for its whole retry ladder — its stage, if any,
            # died with it or ages out via STAGE_TTL) and surface the
            # atomic abort
            for ok, c in zip(prepared, self.clients):
                if not ok:
                    continue
                try:
                    c.abort_txn(txn_id)
                except Exception:  # noqa: BLE001 — cleanup is best-effort
                    pass
            self._m_commit_aborts.inc()
            emit_event("ps.commit_aborted", txn_id=txn_id,
                       shards_total=len(self.clients),
                       reason=str(err))
            # CommitAbortedError means "safe to retry the whole push" —
            # only wrap errors that ARE transient (connection-shaped).
            # A validation rejection (wrong arity/shapes: ValueError
            # from the socket transport, HTTP 4xx) can never succeed on
            # a resend; wrapping it would send callers into a retry
            # spin, so it propagates typed after the abort fan-out.
            if isinstance(err, _TRANSIENT) and not (
                    isinstance(err, urllib.error.HTTPError)
                    and err.code < 500):
                raise CommitAbortedError(
                    f"sharded push aborted in prepare phase: {err}"
                ) from err
            raise

        def commit_one(c, p):
            def call():
                try:
                    return c.commit_txn(txn_id)
                except UnknownTxnError:
                    # the shard failed over between prepare and commit:
                    # the staged delta died with the old primary —
                    # re-prepare this shard's slice and commit again
                    c.prepare_frame(p, kind, txn_id)
                    return c.commit_txn(txn_id)
            return call

        outcomes = [None] * len(self.clients)

        def record(i, fn):
            def call():
                outcomes[i] = fn()
            return call

        try:
            self._fanout.run([record(i, commit_one(c, p)) for i, (c, p)
                              in enumerate(zip(self.clients, parts))])
        except BaseException as err:
            # commit-phase exhaustion after every shard prepared: the
            # committed shards hold the update, the failed one may not
            # — torn, but VISIBLY so (its generation lags, which the
            # coherence check vetoes). Distinct from the legacy event:
            # ps.sharded_push_torn never fires on the 2PC path.
            raise TornPushError(
                f"commit phase failed after all shards prepared: {err}",
                ["applied" if o is not None else f"failed: {err}"
                 for o in outcomes]) from err
        return max(gen for gen, _version in outcomes)

    def _push_frame_legacy(self, parts, kind: int, update_id: str):
        from ..obs.events import emit as emit_event

        applied = [False] * len(self.clients)
        errors: Dict[int, BaseException] = {}

        def push_one(i, c, p):
            def call():
                try:
                    c.push_frame(p, kind, update_id=update_id)
                except BaseException as err:
                    errors[i] = err
                    raise
                applied[i] = True
            return call

        try:
            self._fanout.run([push_one(i, c, p) for i, (c, p)
                              in enumerate(zip(self.clients, parts))])
        except BaseException as err:
            if any(applied):
                emit_event("ps.sharded_push_torn",
                           shards_applied=sum(applied),
                           shards_total=len(applied))
                raise TornPushError(
                    f"sharded push torn: {sum(applied)}/{len(applied)} "
                    f"shards applied before {err}",
                    ["applied" if ok else
                     f"failed: {errors.get(i, err)}"
                     for i, ok in enumerate(applied)]) from err
            raise

    def health_check(self) -> bool:
        return all(self._fanout.run([c.health_check
                                     for c in self.clients]))

    def close(self):
        for c in self.clients:
            c.close()
        self._fanout.close()


class ShardedServerGroup:
    """N parameter servers (one transport) on ports ``port..port+N-1``,
    each holding one shard of the weight list.

    Presents the single-server admin surface (``start``/``stop``/
    ``snapshot``/``restore``/``num_updates``) plus the per-shard
    operations ``ps_auto_restart`` supervision needs: a dead shard is
    rebuilt from its own snapshot on its own port
    (:meth:`restart_shard`) while the others keep serving.
    """

    def __init__(self, transport, model: Dict[str, Any], port: int,
                 mode: str, num_shards: int, standby: bool = False,
                 **kwargs):
        self.transport = transport
        self.port = int(port)
        self.mode = mode
        self.kwargs = dict(kwargs)
        self.plan = ShardPlan.plan(model["weights"], num_shards)
        self._shard_models = self.plan.shard_model(model)
        self.servers = [
            transport.create_server(self._shard_models[i], self.port + i,
                                    mode, shard=i, **self.kwargs)
            for i in range(self.plan.num_shards)]
        #: hot-standby failover: one warm standby per shard on ports
        #: ``port+N .. port+2N-1``, fed by the primary's applied-delta
        #: stream; armed lazily in :meth:`start` (the standby primes
        #: itself from the primary's snapshot, so arming before the
        #: primaries serve keeps the pair trivially in sync)
        self.standby = bool(standby)
        self.standbys: List[Optional[Any]] = [None] * self.plan.num_shards
        from ..obs.metrics import default_registry

        self._m_failovers = default_registry().counter(
            "ps_failovers_total",
            "standby promotions onto a dead primary's port",
            labels=("shard",))

    @property
    def num_shards(self) -> int:
        return self.plan.num_shards

    @property
    def num_updates(self) -> int:
        """Progress signal: the MINIMUM applied-update count across
        shards — every worker push touches every shard, so the slowest
        shard's counter is the number of fully-landed updates."""
        return min(s.num_updates for s in self.servers)

    def standby_port(self, i: int) -> int:
        """The shard-``i`` standby's port (primaries occupy
        ``port..port+N-1``, standbys the next N ports)."""
        return self.port + self.plan.num_shards + int(i)

    def _arm_standby(self, i: int):
        from .replication import ShardStandby

        self.standbys[i] = ShardStandby(
            self.transport, self.servers[i], self.standby_port(i),
            self.mode, i, self._shard_models[i], **self.kwargs)

    def start(self):
        started = []
        try:
            for s in self.servers:
                s.start()
                started.append(s)
            if self.standby:
                for i in range(self.plan.num_shards):
                    self._arm_standby(i)
        except BaseException:
            for sb in self.standbys:
                if sb is not None:
                    sb.stop()
            self.standbys = [None] * self.plan.num_shards
            for s in started:      # no half-started group left behind
                try:
                    s.stop()
                except Exception:
                    pass
            raise

    def stop(self):
        first: Optional[BaseException] = None
        for sb in self.standbys:
            if sb is not None:
                try:
                    sb.stop()
                except Exception as err:  # noqa: BLE001
                    first = first or err
        self.standbys = [None] * self.plan.num_shards
        for s in self.servers:
            try:
                s.stop()
            except Exception as err:  # stop every shard before raising
                first = first or err
        if first is not None:
            raise first

    def get_weights(self) -> List[np.ndarray]:
        """The full reassembled weight list (driver-side convenience —
        remote callers use :class:`ShardedParameterClient`)."""
        return self.plan.merge([s.get_weights() for s in self.servers])

    def snapshot(self) -> Dict[str, Any]:
        return {"shards": [s.snapshot() for s in self.servers]}

    def restore(self, snapshot: Dict[str, Any]):
        shards = snapshot["shards"]
        if len(shards) != len(self.servers):
            raise ValueError(
                f"snapshot has {len(shards)} shards, group has "
                f"{len(self.servers)}")
        for s, snap in zip(self.servers, shards):
            s.restore(snap)

    def snapshot_shard(self, i: int) -> Dict[str, Any]:
        return self.servers[i].snapshot()

    def promote_shard(self, i: int):
        """Hot-standby failover for ONE shard: promote the standby's
        CURRENT state onto the dead primary's port (zero applied-update
        loss — every acked delta is already on the standby), bump the
        fencing epoch so the dead primary's late traffic is rejected if
        it turns out to be a zombie, and re-arm a FRESH standby behind
        the promoted server. Returns the new primary, or ``None`` when
        no healthy standby exists (the caller falls back to
        :meth:`restart_shard`)."""
        from ..obs.events import emit as emit_event

        standby = self.standbys[i]
        if standby is None or not standby.healthy():
            return None
        old = self.servers[i]
        old_epoch = getattr(old, "epoch", 0)
        lag = standby.replicator.lag
        try:
            old.stop()          # fence the corpse off its port
        except Exception:  # noqa: BLE001 — already dead is the point
            pass
        server = standby.promote(self.port + i)
        if server is None:
            # the standby declined (undrained backlog): retire it and
            # let the caller take the snapshot-restart fallback, which
            # realigns the generation marker and re-arms a fresh standby
            standby.stop()
            self.standbys[i] = None
            return None
        self.servers[i] = server
        self.standbys[i] = None
        self._arm_standby(i)
        self._m_failovers.labels(shard=str(i)).inc()
        emit_event("ps.failover", shard=i, old_epoch=int(old_epoch),
                   new_epoch=int(server.epoch), lag_at_promotion=lag,
                   generation=int(server.generation))
        return server

    def restart_shard(self, i: int, snapshot: Dict[str, Any]):
        """Kill→restart recovery for ONE shard — the NO-STANDBY
        fallback: stop whatever is left of the old server, rebuild it
        from ``snapshot`` on the same port, start it. Workers reconnect
        through their sub-clients' retry path; the restored idempotency
        window keeps in-flight resends deduplicated.

        Post-snapshot deltas are LOST (the documented lossy trade the
        hot standby exists to close), so the restarted shard's
        generation marker is REALIGNED to the most-advanced surviving
        shard's — without it the generation-coherence check would veto
        every pull forever; with it the loss stays exactly the
        pre-standby semantics (one stale slice until new pushes land),
        surfaced as a ``ps.generation_realigned`` event."""
        from ..obs.events import emit as emit_event

        try:
            self.servers[i].stop()
        except Exception:
            pass  # already dead — the port is what matters
        server = self.transport.create_server(
            {"model": self._shard_models[i].get("model"),
             "weights": snapshot["weights"]},
            self.port + i, self.mode, shard=i, **self.kwargs)
        server.restore(snapshot)
        survivors = [s.generation_info() for j, s in
                     enumerate(self.servers)
                     if j != i and hasattr(s, "generation_info")]
        if survivors:
            target = max(survivors)
            if target != server.generation_info():
                emit_event("ps.generation_realigned", shard=i,
                           from_generation=int(server.generation),
                           to_generation=int(target[0]))
                server.adopt_generation(*target)
        server.start()
        self.servers[i] = server
        # a standby for the dead primary tracked a timeline that no
        # longer exists — retire it and re-arm against the restarted
        # server so the shard is covered again
        if self.standby:
            old_sb = self.standbys[i]
            if old_sb is not None:
                old_sb.stop()
            self._arm_standby(i)
        return server
