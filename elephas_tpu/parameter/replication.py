"""Hot-standby replication and failover for the parameter plane.

Snapshot-restart recovery (:meth:`~elephas_tpu.tpu_model.TPUModel.
_ps_supervision`) silently loses every delta applied since the last
snapshot — the one remaining single-point-of-data-loss in the
train-to-serve loop. This module closes it:

- :class:`ShardReplicator` rides a primary server's applied-delta hook
  and forwards EVERY applied delta to a warm standby over the ordinary
  transport (new ``replicate`` RPC), deduplicated by the same 32-byte
  update ids client retries use. Replication is synchronous while the
  standby is healthy — an acked push is already on the standby when the
  ack leaves — and degrades to a bounded catch-up backlog when the
  standby flaps (``ps_replication_lag_updates`` is the backlog depth).
- :class:`ShardStandby` owns one shard's standby server (built from the
  primary's snapshot, so counters and the idempotency window carry
  over) plus the replicator feeding it, and implements
  :meth:`ShardStandby.promote`: rebuild the standby's CURRENT state as
  a new primary on the dead primary's port — zero applied-update loss —
  with the shard's **fencing epoch** bumped, so late replication
  traffic from a zombie predecessor (declared dead, still running) is
  rejected (:class:`~elephas_tpu.parameter.client.FencedEpochError`)
  instead of corrupting the new timeline.

Orchestration (which shard gets a standby, when to promote, re-arming a
fresh standby behind the promoted primary) lives in
:class:`~elephas_tpu.parameter.sharding.ShardedServerGroup` /
``_sharded_ps_supervision``; this module is the per-shard machinery.
"""
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs.events import emit as emit_event
from ..obs.metrics import default_registry
from ..utils.tensor_codec import KIND_DELTA
from .client import BaseParameterClient, FencedEpochError

_LOG = logging.getLogger(__name__)

__all__ = ["ShardReplicator", "ShardStandby"]


class ShardReplicator:
    """Forwards a primary's applied deltas to its standby.

    Attaches to ``primary.set_applied_hook``; each hook call tries a
    SYNCHRONOUS ``replicate_frame`` first (sub-millisecond on loopback,
    and the reason a promoted standby is bit-identical: the ack the
    pusher saw implies the standby holds the delta). On failure the
    delta is COPIED onto a bounded backlog and a background thread
    retries in order — resends carry the original update ids, so the
    standby's idempotency window makes catch-up safe. A
    :class:`FencedEpochError` from the standby means THIS primary has
    been failed over (it is the zombie): the replicator stops
    permanently and drops its backlog.
    """

    #: backlog bound: a standby that stays dark longer than this many
    #: parked deltas stops accumulating (oldest kept — they are the
    #: ones the standby is missing first) and the shard is flagged
    #: degraded, steering promotion back to the snapshot fallback
    MAX_BACKLOG = 256

    def __init__(self, primary, standby_client: BaseParameterClient,
                 shard: str = "0"):
        self.primary = primary
        self.client = standby_client
        self.shard = str(shard)
        self.fenced = False
        self.degraded = False
        self._backlog: List[tuple] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        reg = default_registry()
        self._g_lag = reg.gauge(
            "ps_replication_lag_updates",
            "applied deltas acked by the primary but not yet on its "
            "standby (catch-up backlog depth)",
            labels=("shard",)).labels(shard=self.shard)
        self._m_pushes = reg.counter(
            "ps_replication_pushes_total",
            "deltas forwarded primary -> standby, by outcome",
            labels=("shard", "status"))
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True,
            name=f"elephas-tpu-ps-replica-{self.shard}")
        self._thread.start()
        primary.set_applied_hook(self._on_applied)

    # ------------------------------------------------------------- hook
    def _on_applied(self, update_id: str, delta):
        if self.fenced or self._stop.is_set():
            return
        with self._lock:
            backlogged = bool(self._backlog)
        if not backlogged:
            try:
                self.client.replicate_frame(delta, KIND_DELTA, update_id,
                                            self.primary.epoch)
                self._m_pushes.labels(shard=self.shard,
                                      status="ok").inc()
                return
            except FencedEpochError:
                self._fence()
                return
            except Exception:  # noqa: BLE001 — park and catch up
                pass
        # the hook's delta arrays are views of the request's receive
        # buffer — copy before the frame dies
        with self._lock:
            if len(self._backlog) < self.MAX_BACKLOG:
                self._backlog.append(
                    (update_id, [np.array(d, dtype=np.float32, copy=True)
                                 for d in delta]))
                self._m_pushes.labels(shard=self.shard,
                                      status="parked").inc()
            else:
                self.degraded = True  # standby can no longer catch up
                self._m_pushes.labels(shard=self.shard,
                                      status="dropped").inc()
            self._g_lag.set(float(len(self._backlog)))
        self._wake.set()

    def kick(self):
        """Nudge the catch-up thread (the standby just came up)."""
        self._wake.set()

    def _fence(self):
        self.fenced = True
        with self._lock:
            self._backlog.clear()
            self._g_lag.set(0.0)
        self._m_pushes.labels(shard=self.shard, status="fenced").inc()
        _LOG.warning("replicator for shard %s fenced: this primary was "
                     "failed over", self.shard)

    # ------------------------------------------------------- catch-up
    def _drain_loop(self):
        while not self._stop.is_set():
            self._wake.wait(timeout=0.25)
            self._wake.clear()
            while not (self._stop.is_set() or self.fenced):
                with self._lock:
                    if not self._backlog:
                        break
                    update_id, delta = self._backlog[0]
                try:
                    self.client.replicate_frame(delta, KIND_DELTA,
                                                update_id,
                                                self.primary.epoch)
                except FencedEpochError:
                    self._fence()
                    return
                except Exception:  # noqa: BLE001 — standby still down
                    time.sleep(0.1)
                    continue
                with self._lock:
                    # head unchanged by construction: this thread is the
                    # only consumer and _on_applied only appends
                    self._backlog.pop(0)
                    self._g_lag.set(float(len(self._backlog)))
                self._m_pushes.labels(shard=self.shard,
                                      status="caught_up").inc()

    # ------------------------------------------------------------ admin
    @property
    def lag(self) -> int:
        with self._lock:
            return len(self._backlog)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until the backlog drains (True) or ``timeout`` passes
        (False) — promotion calls this so a flapped-then-recovered
        standby is fully caught up before it takes over."""
        deadline = time.monotonic() + timeout
        self._wake.set()
        while time.monotonic() < deadline:
            if self.fenced:
                return False
            with self._lock:
                if not self._backlog:
                    return True
            time.sleep(0.01)
        return False

    def stop(self):
        """Detach from the primary and stop the catch-up thread (the
        client is the caller's to close — ShardStandby owns it)."""
        try:
            if self.primary._applied_hook == self._on_applied:
                self.primary.set_applied_hook(None)
        except Exception:  # noqa: BLE001 — primary may be half-dead
            pass
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)


class ShardStandby:
    """One shard's warm standby: a full parameter server (same
    transport, its own port) primed from the primary's snapshot, fed by
    a :class:`ShardReplicator`, promotable in place of a dead primary.
    """

    def __init__(self, transport, primary, port: int, mode: str,
                 shard_index: int, shard_model: Dict[str, Any],
                 **kwargs):
        self.transport = transport
        self.port = int(port)
        self.mode = mode
        self.shard_index = int(shard_index)
        self.shard_model = shard_model
        self.kwargs = dict(kwargs)
        # ORDER MATTERS when the primary is live (re-arming behind a
        # promoted/restarted server): the replicator attaches FIRST, so
        # a delta applied while the standby is still being built parks
        # on the backlog (its sync send finds nothing listening yet)
        # instead of vanishing into the snapshot/hook gap; then the
        # snapshot is taken and the standby built from it. A delta
        # captured by BOTH (in the snapshot and on the backlog) is
        # deduplicated by the standby's idempotency window, which rides
        # the snapshot — so the pair cannot diverge in either
        # direction. Fail-fast client (no retries): replication must
        # park and catch up, not stall the primary's push ack behind a
        # retry ladder.
        client = transport.create_client(self.port, timeout=5.0,
                                         max_retries=0, deadline=5.0)
        self.replicator = ShardReplicator(primary, client,
                                          shard=str(shard_index))
        snapshot = primary.snapshot()
        self.server = transport.create_server(
            {"model": shard_model.get("model"),
             "weights": snapshot["weights"]},
            self.port, mode, shard=shard_index, **self.kwargs)
        self.server.restore(snapshot)
        self.server.start()
        self.replicator.kick()     # drain anything parked while building

    def healthy(self) -> bool:
        """Promotable: the standby answers its probe, the replicator
        never overflowed (``degraded`` means acked deltas were dropped
        — a snapshot restart is no worse then), and it was not fenced
        off by a newer timeline."""
        if self.replicator.fenced or self.replicator.degraded:
            return False
        return self.replicator.client.health_check()

    def promote(self, primary_port: int):
        """Zero-loss failover: drain the catch-up backlog, then rebuild
        the standby's CURRENT state as a new primary on
        ``primary_port`` with the fencing epoch bumped. Returns the new
        primary server (started), or ``None`` when the backlog would
        not drain — promoting with acked deltas still parked would
        silently break the zero-loss claim AND leave this shard's
        generation digest diverged from its siblings forever, whereas
        the snapshot fallback realigns generations explicitly. The
        standby server itself is stopped — its port hosts the NEXT
        standby the group re-arms."""
        # drain FIRST (the catch-up thread is still alive), then detach
        # from the (dead or zombie) primary
        if not self.replicator.flush(timeout=5.0):
            self.replicator.degraded = True
            emit_event("ps.promotion_declined", shard=self.shard_index,
                       backlog=self.replicator.lag,
                       fenced=self.replicator.fenced)
            _LOG.warning(
                "shard %d standby declined promotion: %d acked deltas "
                "still parked after the flush window (falling back to "
                "snapshot restart)", self.shard_index,
                self.replicator.lag)
            return None
        self.replicator.stop()
        snapshot = self.server.snapshot()
        new_epoch = int(snapshot.get("epoch", 0)) + 1
        server = self.transport.create_server(
            {"model": self.shard_model.get("model"),
             "weights": snapshot["weights"]},
            int(primary_port), self.mode, shard=self.shard_index,
            epoch=new_epoch, **self.kwargs)
        server.restore(snapshot)
        with server._counter_lock:
            server.epoch = new_epoch    # restore only ratchets; pin it
        server.start()
        self.stop(stop_replicator=False)
        return server

    def stop(self, stop_replicator: bool = True):
        if stop_replicator:
            self.replicator.stop()
        try:
            self.replicator.client.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.server.stop()
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
