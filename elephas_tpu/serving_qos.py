"""Multi-tenant QoS: priority classes, weighted fair queueing, quotas.

The serving stack's isolation layer. Everything below this module is
GLOBAL — admission bounds (``max_queue`` / ``max_queued_tokens``) and
the fleet edge's 429s protect the ENGINE, not any one tenant, so a
single heavy tenant flooding the queue degrades every tenant equally.
:class:`TenantQoS` + :class:`FairQueue` make overload degrade
*selectively* instead:

- **Tenants.** Every request carries a ``tenant`` name (``"default"``
  when the client sends none). The engine schedules, meters, and
  sheds per tenant.
- **Priority classes.** ``"low"`` / ``"normal"`` / ``"high"``
  (:data:`PRIORITY_CLASSES`), per tenant with a per-request override.
  A strictly-higher class is admitted first, and — in a paged engine
  with the automatic prefix cache — may PREEMPT a lower class's
  in-flight decode under pool pressure (see
  :meth:`~elephas_tpu.serving_engine.DecodeEngine._preempt_slot`: the
  victim's full KV blocks park in the
  :class:`~elephas_tpu.models.block_cache.BlockCache` and resume as a
  prefix-cache hit, so preemption costs a short remainder prefill, not
  a recompute).
- **Weighted fair queueing.** Admission replaces the FIFO pop with
  deficit-round-robin over QUEUED TOKENS (not request counts — a
  tenant submitting 4x-longer prompts gets 1/4 the admissions at equal
  weight, which is what "fair share of prefill capacity" means).
  Within one priority class, each tenant's long-run admitted-token
  share converges to ``weight / sum(weights of backlogged tenants)``.
- **Quotas.** Per-tenant ``max_queue`` / ``max_queued_tokens`` bounds:
  a breaching submit sheds with a 429 + a quota-aware
  ``retry_after_ms`` (scaled by the OFFENDING tenant's own backlog)
  while under-quota tenants keep admitting — the isolation the global
  bounds cannot give.

``docs/sources/serving-operations.md`` ("Multi-tenant isolation") has
the runbook; the ``tenant_qos`` row in ``benchmarks/baseline_rows.py``
is the measured claim (a flooding heavy tenant vs a light interactive
tenant, QoS on vs off).
"""
import math
from collections import OrderedDict, deque
from typing import Dict, Iterator, List, NamedTuple, Optional

import numpy as np

__all__ = ["TenantQoS", "FairQueue", "QueuedRequest", "PRIORITY_CLASSES",
           "DEFAULT_TENANT"]

#: the named priority classes requests/tenants may carry (larger =
#: more important); integers are also accepted anywhere a class name is
PRIORITY_CLASSES = {"low": 0, "normal": 1, "high": 2}

#: the tenant every request without an explicit ``tenant`` belongs to
DEFAULT_TENANT = "default"

#: metrics label for tenants absent from the QoS config: label domains
#: must stay bounded (clients choose tenant names; the registry caps
#: label sets), so only CONFIGURED tenants get their own label
OTHER_LABEL = "other"


class QueuedRequest(NamedTuple):
    """One queued (not yet admitted) engine request. ``prompt`` is the
    tokens admission will prefill — for a preempted request re-queued
    for resume, that is the ORIGINAL prompt plus every token emitted so
    far (the chain walk then reclaims its parked KV blocks, so resume
    admits like a prefix-cache hit)."""

    rid: int
    prompt: np.ndarray
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    tenant: str
    priority: int
    #: resumable-session id (:mod:`~elephas_tpu.kvtier`), or ``None``.
    #: Informational here — the engine keys its live session map by rid;
    #: carrying it on the queue record keeps preemption requeues whole.
    session: Optional[str] = None


class TenantQoS:
    """The per-tenant serving policy a
    :class:`~elephas_tpu.serving_engine.DecodeEngine` enforces.

    :param tenants: ``{name: spec}`` where spec may hold ``weight``
        (fair-queueing share, > 0), ``priority`` (default class for the
        tenant's requests — a :data:`PRIORITY_CLASSES` name or int),
        ``max_queue`` (quota on the tenant's queued requests) and
        ``max_queued_tokens`` (quota on the tenant's queued prompt
        tokens). Unlisted tenants get the defaults below and fold into
        the ``"other"`` metrics label.
    :param default_weight: weight for unlisted tenants.
    :param default_priority: class for requests that carry none.
    :param preempt: allow a strictly-higher-priority queued request to
        preempt a lower-priority in-flight decode under pool pressure
        (paged engines with the prefix cache only — parking needs the
        block cache; other engines ignore the flag).
    :param quantum_tokens: deficit-round-robin quantum — tokens of
        admission credit a backlogged tenant accrues per scheduling
        round, scaled by its weight.
    """

    def __init__(self, tenants: Optional[Dict[str, Dict]] = None,
                 default_weight: float = 1.0,
                 default_priority="normal", preempt: bool = True,
                 quantum_tokens: int = 64):
        self.tenants: Dict[str, Dict] = {}
        for name, spec in (tenants or {}).items():
            spec = dict(spec or {})
            unknown = set(spec) - {"weight", "priority", "max_queue",
                                   "max_queued_tokens"}
            if unknown:
                raise ValueError(f"unknown tenant spec keys for "
                                 f"{name!r}: {sorted(unknown)}")
            if "weight" in spec and not float(spec["weight"]) > 0:
                raise ValueError(f"tenant {name!r} weight must be > 0")
            if "priority" in spec:
                spec["priority"] = self._parse_class(spec["priority"])
            for bound in ("max_queue", "max_queued_tokens"):
                if spec.get(bound) is not None and int(spec[bound]) < 1:
                    raise ValueError(
                        f"tenant {name!r} {bound} must be >= 1")
            self.tenants[str(name)] = spec
        self.default_weight = float(default_weight)
        if not self.default_weight > 0:
            raise ValueError("default_weight must be > 0")
        self.default_priority = self._parse_class(default_priority)
        self.preempt = bool(preempt)
        self.quantum_tokens = int(quantum_tokens)
        if self.quantum_tokens < 1:
            raise ValueError("quantum_tokens must be >= 1")

    @staticmethod
    def _parse_class(value) -> int:
        if isinstance(value, str):
            try:
                return PRIORITY_CLASSES[value]
            except KeyError:
                raise ValueError(
                    f"unknown priority class {value!r} (one of "
                    f"{sorted(PRIORITY_CLASSES)}, or an int)") from None
        return int(value)

    @classmethod
    def coerce(cls, value) -> Optional["TenantQoS"]:
        """``None`` | :class:`TenantQoS` | ctor-kwargs dict — the
        engine's ``qos=`` parameter accepts all three."""
        if value is None or isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"qos must be a TenantQoS or a kwargs dict, "
                        f"got {type(value).__name__}")

    # ------------------------------------------------------------ policy
    def weight(self, tenant: str) -> float:
        return float(self.tenants.get(tenant, {}).get(
            "weight", self.default_weight))

    def priority(self, tenant: str, override=None) -> int:
        """The request's effective priority class: the tenant's
        configured class, which a per-request override (name or int)
        may only LOWER — priority is an operator-granted property of
        the tenant, and an uncapped override would let any client
        self-escalate past the isolation the policy exists to enforce
        (outranking and even preempting higher-paying tenants)."""
        ceiling = int(self.tenants.get(tenant, {}).get(
            "priority", self.default_priority))
        if override is None:
            return ceiling
        return min(self._parse_class(override), ceiling)

    def quota(self, tenant: str):
        """``(max_queue, max_queued_tokens)`` for ``tenant`` (each
        ``None`` = unbounded)."""
        spec = self.tenants.get(tenant, {})
        mq = spec.get("max_queue")
        mt = spec.get("max_queued_tokens")
        return (None if mq is None else int(mq),
                None if mt is None else int(mt))

    def label(self, tenant: Optional[str]) -> str:
        """The metrics label for ``tenant``: configured tenants (and
        the default tenant) keep their name; everything else folds to
        ``"other"`` so client-chosen names cannot grow a label domain
        past the registry's cardinality bound."""
        if not tenant:
            return DEFAULT_TENANT
        if tenant in self.tenants or tenant == DEFAULT_TENANT:
            return str(tenant)
        return OTHER_LABEL


class FairQueue:
    """The engine's admission queue: plain FIFO without a policy,
    token-budget deficit-round-robin across tenants (within the
    highest backlogged priority class) with one.

    Scheduling rule with a :class:`TenantQoS`:

    1. Requests are FIFO *within* a tenant (one deque per tenant).
    2. Only tenants whose HEAD request is in the highest priority class
       present are candidates — strict priority across classes.
    3. Among candidates, deficit round robin over tokens: each tenant
       carries a deficit counter; every scheduling round adds
       ``quantum_tokens * weight`` and the first tenant (in rotation
       order) whose deficit covers its head request's prompt tokens is
       served, paying the prompt size down from its deficit. A tenant
       whose queue empties forfeits its deficit (no hoarding credit
       while idle — classic DRR). :meth:`peek` computes the same choice
       :meth:`pop` commits, side-effect free, so a paged engine can
       hold the chosen candidate waiting for pool capacity exactly
       like the old FIFO head (no overtaking — no starvation).
    """

    def __init__(self, qos: Optional[TenantQoS] = None):
        self._qos = qos
        self._fifo: deque = deque()            # qos is None
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rr: List[str] = []               # backlogged, rotation order
        self._deficit: Dict[str, float] = {}
        self._tokens: Dict[str, int] = {}      # queued tokens per tenant
        self._len = 0

    # ---------------------------------------------------------- plumbing
    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator[QueuedRequest]:
        if self._qos is None:
            return iter(list(self._fifo))
        return iter([item for t in self._queues
                     for item in self._queues[t]])

    def append(self, item: QueuedRequest) -> None:
        self._push(item, left=False)

    def appendleft(self, item: QueuedRequest) -> None:
        """Queue at the FRONT of the item's tenant lane — a preempted
        request resumes before anything its tenant queued after it."""
        self._push(item, left=True)

    def _push(self, item: QueuedRequest, left: bool) -> None:
        self._len += 1
        if self._qos is None:
            (self._fifo.appendleft if left else self._fifo.append)(item)
            return
        t = item.tenant
        lane = self._queues.get(t)
        if lane is None:
            lane = self._queues[t] = deque()
        if not lane:
            self._rr.append(t)                 # (re)joins the rotation
        (lane.appendleft if left else lane.append)(item)
        self._tokens[t] = self._tokens.get(t, 0) + int(item.prompt.size)

    # --------------------------------------------------------- scheduling
    def _choose(self):
        """(rounds, candidate tenants, winner) of the next DRR grant —
        a pure function of the queue state, so peek() and pop() agree."""
        heads = {t: self._queues[t][0] for t in self._rr}
        top = max(h.priority for h in heads.values())
        cands = [t for t in self._rr if heads[t].priority == top]
        best = None
        for idx, t in enumerate(cands):
            need = int(heads[t].prompt.size)
            d = self._deficit.get(t, 0.0)
            qw = self._qos.quantum_tokens * self._qos.weight(t)
            k = 0 if d >= need else math.ceil((need - d) / qw)
            if best is None or k < best[0]:
                best = (k, idx, t)
        return best[0], cands, best[2]

    def peek(self) -> Optional[QueuedRequest]:
        if self._qos is None:
            return self._fifo[0] if self._fifo else None
        if not self._rr:
            return None
        return self._queues[self._choose()[2]][0]

    def pop(self) -> QueuedRequest:
        if self._qos is None:
            self._len -= 1
            return self._fifo.popleft()
        rounds, cands, winner = self._choose()
        if rounds:
            q = self._qos.quantum_tokens
            for t in cands:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + rounds * q * self._qos.weight(t))
        item = self._queues[winner].popleft()
        self._len -= 1
        self._tokens[winner] -= int(item.prompt.size)
        self._deficit[winner] = (self._deficit.get(winner, 0.0)
                                 - int(item.prompt.size))
        self._rr.remove(winner)
        if self._queues[winner]:
            self._rr.append(winner)            # rotate to the back
        else:
            del self._queues[winner]           # idle: forfeit the credit
            self._deficit.pop(winner, None)
            self._tokens.pop(winner, None)
        return item

    # ----------------------------------------------------------- removal
    def remove_if(self, pred) -> List[QueuedRequest]:
        """Drop (and return) every queued item matching ``pred`` — the
        expired-deadline sweep and cancel path."""
        if self._qos is None:
            return self._remove_fifo(pred)
        dropped: List[QueuedRequest] = []
        for t in list(self._queues):
            lane = self._queues[t]
            keep = deque()
            for item in lane:
                if pred(item):
                    dropped.append(item)
                    self._tokens[t] -= int(item.prompt.size)
                else:
                    keep.append(item)
            if len(keep) != len(lane):
                self._queues[t] = keep
                if not keep:
                    del self._queues[t]
                    self._rr.remove(t)
                    self._deficit.pop(t, None)
                    self._tokens.pop(t, None)
        self._len -= len(dropped)
        return dropped

    def _remove_fifo(self, pred) -> List[QueuedRequest]:
        dropped, keep = [], deque()
        for item in self._fifo:
            (dropped.append if pred(item) else keep.append)(item)
        self._fifo = keep
        self._len -= len(dropped)
        return dropped

    def remove_rid(self, rid: int) -> Optional[QueuedRequest]:
        out = self.remove_if(lambda item: item.rid == rid)
        return out[0] if out else None

    # ----------------------------------------------------------- queries
    def tenant_depth(self, tenant: str) -> int:
        if self._qos is None:
            return sum(1 for item in self._fifo if item.tenant == tenant)
        lane = self._queues.get(tenant)
        return 0 if lane is None else len(lane)

    def tenant_queued_tokens(self, tenant: str) -> int:
        if self._qos is None:
            return sum(int(item.prompt.size) for item in self._fifo
                       if item.tenant == tenant)
        return int(self._tokens.get(tenant, 0))

    def tokens_for_label(self, label: str, qos: TenantQoS) -> int:
        """Queued tokens across every tenant folding into metrics
        ``label`` (the ``serving_tenant_queued_tokens`` gauge callback
        — ``"other"`` aggregates all unconfigured tenants)."""
        return sum(n for t, n in self._tokens.items()
                   if qos.label(t) == label)

    def live_tenants(self) -> List[str]:
        """Tenants with queued work right now (stats surface)."""
        if self._qos is None:
            return sorted({item.tenant for item in self._fifo})
        return list(self._queues)
