"""Serving conveniences: strings in -> strings out.

``TextGenerator`` ties a tokenizer to the transformer LM's batched
ragged-prompt decode loop: prompts of different lengths batch into one
jitted scan (right-padded + per-row lengths), with the full sampling
suite (temperature / top-k / nucleus / repetition penalty).
"""
from typing import List, Optional, Sequence

import jax
import numpy as np

from .models.transformer import TransformerConfig, generate
from .utils.text import ByteTokenizer

__all__ = ["TextGenerator"]


class TextGenerator:
    """Batched text generation over a parameter pytree + config.

    :param params: transformer parameter pytree (may be mesh-sharded —
        the decode scan partitions through GSPMD)
    :param config: the model's :class:`TransformerConfig`
    :param tokenizer: object with ``encode(str) -> List[int]`` and
        ``decode(ids) -> str`` (default: :class:`ByteTokenizer`)
    """

    def __init__(self, params, config: TransformerConfig, tokenizer=None):
        self.params = params
        self.config = config
        self.tokenizer = tokenizer or ByteTokenizer()

    def __call__(self, prompts: Sequence[str], max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 repetition_penalty: float = 1.0,
                 seed: int = 0,
                 stop_id: Optional[int] = None) -> List[str]:
        tok = self.tokenizer
        encoded = [tok.encode(p) for p in prompts]
        lens = np.asarray([len(e) for e in encoded], np.int32)
        if lens.min() < 1:
            raise ValueError("prompts must encode to at least one token")
        lmax = int(lens.max())
        pad = getattr(tok, "pad_id", 0)
        batch = np.full((len(encoded), lmax), pad, np.int32)
        for i, e in enumerate(encoded):
            batch[i, :len(e)] = e

        out = np.asarray(generate(
            self.params, batch, int(max_new_tokens), self.config,
            temperature=temperature, key=jax.random.PRNGKey(seed),
            top_k=top_k, top_p=top_p,
            repetition_penalty=repetition_penalty,
            prompt_lengths=lens))

        stop = stop_id if stop_id is not None else getattr(tok, "eos_id",
                                                           None)
        texts = []
        for row in out:
            ids = list(row)
            if stop is not None and stop in ids:
                ids = ids[:ids.index(stop)]
            texts.append(tok.decode(ids))
        return texts
