"""Serving conveniences: strings in -> strings out.

``TextGenerator`` ties a tokenizer to the transformer LM's batched
ragged-prompt decode loop: prompts of different lengths batch into one
jitted scan (right-padded + per-row lengths), with the full sampling
suite (temperature / top-k / nucleus / repetition penalty).

Overload safety for the BLOCKING path: ``max_batch_prompts`` /
``max_batch_tokens`` bound what one call may dispatch (an oversized
batch raises :class:`~elephas_tpu.serving_engine.QueueFullError` with a
suggested split instead of monopolizing the chip), and ``deadline_ms``
refuses to dispatch work whose deadline already passed during
tokenization/queueing upstream. The fused decode scan itself is NOT
preemptible — once dispatched it runs to completion; callers that need
mid-decode deadlines and per-request shedding serve through
:class:`~elephas_tpu.serving_engine.DecodeEngine`, which enforces both.
"""
import time
from typing import List, Optional, Sequence

import jax
import numpy as np

from .models.transformer import TransformerConfig, generate
from .serving_engine import DeadlineExceededError, QueueFullError
from .utils.text import ByteTokenizer

__all__ = ["TextGenerator"]


class TextGenerator:
    """Batched text generation over a parameter pytree + config.

    :param params: transformer parameter pytree (may be mesh-sharded —
        the decode scan partitions through GSPMD)
    :param config: the model's :class:`TransformerConfig`
    :param tokenizer: object with ``encode(str) -> List[int]`` and
        ``decode(ids) -> str`` (default: :class:`ByteTokenizer`)
    :param draft_params: optional draft-model parameters enabling
        speculative decoding (draft proposes, target verifies in one
        block forward — up to ``1 + gamma*acceptance`` tokens per
        target weight read). Used when the batch's prompts encode to
        equal lengths and no top-k/top-p/repetition filter is
        requested; other calls fall back to the plain decode scan.
    :param draft_config: the draft model's config (same vocabulary)
    :param gamma: draft tokens proposed per verify round
    :param max_batch_prompts: admission bound on prompts per call; an
        oversized batch raises :class:`QueueFullError` (``None`` =
        unbounded)
    :param max_batch_tokens: admission bound on the TOTAL encoded
        prompt tokens per call — the real memory/prefill cost a prompt
        count alone cannot see
    """

    def __init__(self, params, config: TransformerConfig, tokenizer=None,
                 draft_params=None, draft_config=None, gamma: int = 4,
                 max_batch_prompts: Optional[int] = None,
                 max_batch_tokens: Optional[int] = None):
        self.params = params
        self.config = config
        self.tokenizer = tokenizer or ByteTokenizer()
        self.max_batch_prompts = (None if max_batch_prompts is None
                                  else int(max_batch_prompts))
        if (self.max_batch_prompts is not None
                and self.max_batch_prompts < 1):
            raise ValueError("max_batch_prompts must be None or >= 1")
        self.max_batch_tokens = (None if max_batch_tokens is None
                                 else int(max_batch_tokens))
        if (self.max_batch_tokens is not None
                and self.max_batch_tokens < 1):
            raise ValueError("max_batch_tokens must be None or >= 1")
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config go together")
        if draft_config is not None:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {config.vocab_size}")
            if gamma < 1:
                raise ValueError("gamma must be >= 1")
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.gamma = int(gamma)

    def __call__(self, prompts: Sequence[str], max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 repetition_penalty: float = 1.0,
                 seed: int = 0,
                 stop_id: Optional[int] = None,
                 stop_sequences: Optional[Sequence[str]] = None,
                 deadline_ms: Optional[float] = None
                 ) -> List[str]:
        """Generate continuations for ``prompts``. ``stop_sequences``
        truncates each output at the earliest occurrence of any of the
        given strings (the stop text itself is dropped) — multi-token
        stop phrases the single-id ``stop_id`` cannot express.

        ``deadline_ms`` bounds ADMISSION: if tokenizing the batch alone
        eats the deadline, :class:`DeadlineExceededError` is raised
        before any device work is dispatched. The fused scan itself is
        not preemptible — use :class:`DecodeEngine` deadlines for
        mid-decode enforcement."""
        t0 = time.monotonic()
        if deadline_ms is not None and not deadline_ms > 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        tok = self.tokenizer
        if (self.max_batch_prompts is not None
                and len(prompts) > self.max_batch_prompts):
            raise QueueFullError(
                f"batch of {len(prompts)} prompts exceeds "
                f"max_batch_prompts={self.max_batch_prompts}; split into "
                f"{-(-len(prompts) // self.max_batch_prompts)} calls")
        encoded = [tok.encode(p) for p in prompts]
        lens = np.asarray([len(e) for e in encoded], np.int32)
        if lens.min() < 1:
            raise ValueError("prompts must encode to at least one token")
        total_tokens = int(lens.sum())
        if (self.max_batch_tokens is not None
                and int(lens.max()) > self.max_batch_tokens):
            # permanently inadmissible — splitting the batch cannot get
            # a single over-bound prompt under the cap, so a retryable
            # QueueFullError would have well-behaved clients retrying
            # forever (same rule as DecodeEngine's max_queued_tokens)
            raise ValueError(
                f"a single prompt of {int(lens.max())} tokens exceeds "
                f"max_batch_tokens={self.max_batch_tokens} — it could "
                "never be dispatched")
        if (self.max_batch_tokens is not None
                and total_tokens > self.max_batch_tokens):
            raise QueueFullError(
                f"batch of {total_tokens} prompt tokens exceeds "
                f"max_batch_tokens={self.max_batch_tokens}; split the "
                "batch or trim the prompts")
        if (deadline_ms is not None
                and (time.monotonic() - t0) * 1000.0 >= deadline_ms):
            raise DeadlineExceededError(
                f"deadline of {deadline_ms}ms expired during admission "
                "(before any device work was dispatched)")
        lmax = int(lens.max())
        pad = getattr(tok, "pad_id", 0)
        batch = np.full((len(encoded), lmax), pad, np.int32)
        for i, e in enumerate(encoded):
            batch[i, :len(e)] = e

        uniform = int(lens.min()) == lmax
        plain_sampling = (top_k is None and top_p is None
                          and repetition_penalty == 1.0)
        # the speculative cache needs gamma slack past the last token;
        # near-max_seq_len calls stay on the plain scan instead of
        # failing where generate() would succeed
        fits = all(lmax + int(max_new_tokens) + self.gamma <= c.max_seq_len
                   for c in ((self.config, self.draft_config)
                             if self.draft_config is not None
                             else (self.config,)))
        if (self.draft_params is not None and uniform and plain_sampling
                and fits):
            from .models.speculative import speculative_generate

            out = np.asarray(speculative_generate(
                self.params, self.draft_params, batch,
                int(max_new_tokens), self.config, self.draft_config,
                gamma=self.gamma, temperature=temperature,
                key=jax.random.PRNGKey(seed)))
        else:
            out = np.asarray(generate(
                self.params, batch, int(max_new_tokens), self.config,
                temperature=temperature, key=jax.random.PRNGKey(seed),
                top_k=top_k, top_p=top_p,
                repetition_penalty=repetition_penalty,
                prompt_lengths=lens))

        stop = stop_id if stop_id is not None else getattr(tok, "eos_id",
                                                           None)
        texts = []
        for row in out:
            ids = list(row)
            if stop is not None and stop in ids:
                ids = ids[:ids.index(stop)]
            text = tok.decode(ids)
            if stop_sequences:
                # empty stops are skipped: find("") is 0 for every
                # string and would silently blank all outputs
                cut = min((idx for idx in (text.find(s)
                                           for s in stop_sequences if s)
                           if idx >= 0), default=-1)
                if cut >= 0:
                    text = text[:cut]
            texts.append(text)
        return texts
