"""Per-partition training workers.

The reference ships these closures to Spark executors via
``rdd.mapPartitions`` (``elephas/worker.py:11-131``). Here workers are
driven by the single-controller :class:`~elephas_tpu.tpu_model.TPUModel`:

- Synchronous training normally runs *all* workers inside one jitted,
  mesh-sharded program (:class:`~elephas_tpu.parallel.SyncAverageTrainer`);
  :class:`SyncWorker` is the per-partition scalar implementation of the
  same semantics, used as a reference/fallback path and for tests.
- :class:`AsyncWorker` mirrors the reference's asynchronous executor loop
  exactly: pull global weights from the parameter server, train locally
  for one epoch (or one batch), push the weight delta
  (``elephas/worker.py:76-131``). Workers run as coordinator-host threads,
  each driving jit-compiled local steps.
- With ``overlap=True`` (or ``accum_batches > 1``) the batch-frequency
  loop switches to a TPU-friendly schedule: parameters stay on device
  between steps, the jitted step is compiled once, weight deltas
  accumulate on device for ``accum_batches`` steps, and a background
  :class:`_AsyncCommunicator` thread pushes deltas / prefetches fresh
  global weights (double-buffered) so the chip never idles on an RPC —
  the fix for the reference's 2-blocking-RPCs-per-batch throughput
  killer (``elephas/worker.py:117-127``).
"""
import queue
import threading
from typing import Any, Dict, List, Optional, Union

import jax
import numpy as np

from .models import deserialize_optimizer, model_from_json
from .parameter import BaseParameterClient
from .utils.faults import fault_site
from .utils.functional_utils import subtract_params
from .utils.prefetch import prefetch_to_device
from .utils.tensor_codec import KIND_DELTA as _KIND_DELTA
from .utils.tensor_codec import KIND_DELTA_Q8 as _KIND_DELTA_Q8


class SyncWorker:
    """Train a full local model copy on one partition; return the weight
    delta and training history (parity: ``elephas/worker.py:11-49``)."""

    def __init__(self, json_config: str, parameters: List[np.ndarray],
                 train_config: Dict[str, Any], master_optimizer,
                 master_loss, master_metrics,
                 custom_objects: Optional[Dict] = None,
                 compute_dtype: Optional[str] = None):
        self.json = json_config
        self.parameters = parameters
        self.train_config = dict(train_config)
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects or {}
        self.compute_dtype = compute_dtype
        self.model = None

    def train(self, x_train: np.ndarray, y_train: np.ndarray):
        """Returns ``[delta, history_dict_or_None]``."""
        history = None
        self.model = model_from_json(self.json, self.custom_objects)
        self.model.compile(optimizer=deserialize_optimizer(self.master_optimizer),
                           loss=self.master_loss, metrics=self.master_metrics,
                           custom_objects=self.custom_objects,
                           compute_dtype=self.compute_dtype)
        self.model.set_weights(self.parameters)

        weights_before = self.model.get_weights()
        batch_size = self.train_config.get("batch_size", 32)
        if x_train.shape[0] > batch_size:
            history = self.model.fit(x_train, y_train, **self.train_config)
        weights_after = self.model.get_weights()
        deltas = subtract_params(weights_before, weights_after)
        return [deltas, history.history if history else None]


class _AsyncCommunicator:
    """Background RPC thread owning the parameter-server client.

    Commands (``push`` a delta, ``pull`` fresh weights) execute FIFO off
    the compute thread, so device steps overlap wire I/O. Pulled weights
    land in a single-slot "latest" buffer the compute loop adopts at its
    next accumulation boundary — classic double buffering. A transport
    error parks the thread and re-raises on the compute thread's next
    interaction, preserving the client's failure-detection semantics.
    """

    #: max queued commands — a slower-than-compute server back-pressures
    #: the training loop instead of accumulating unbounded host copies of
    #: the weights in the queue
    MAX_QUEUED = 8

    def __init__(self, client: BaseParameterClient):
        self.client = client
        self._cmds: "queue.Queue" = queue.Queue(maxsize=self.MAX_QUEUED)
        self._latest: Optional[tuple] = None
        self._pushes_done = 0
        self._lock = threading.Lock()
        self._fresh = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="elephas-tpu-async-comm")
        self._thread.start()

    def _run(self):
        while True:
            cmd = self._cmds.get()
            if cmd is None:
                return
            kind, payload = cmd
            try:
                if kind == "push":
                    self.client.update_parameters(payload)
                    self._pushes_done += 1
                elif kind == "push_frame":
                    self.client.push_frame(*payload)
                    self._pushes_done += 1
                else:
                    weights = self.client.get_parameters()
                    with self._lock:
                        # tag the snapshot with how many of OUR pushes it
                        # reflects (FIFO: every push queued before this
                        # pull has executed) — the compute loop must not
                        # adopt a snapshot missing its own latest push,
                        # which would roll back local progress
                        self._latest = (weights, self._pushes_done)
                    self._fresh.set()
            except BaseException as err:  # surfaced on the compute thread
                self._error = err
                self._fresh.set()  # unblock a waiting take_latest
                return

    def _check(self):
        if self._error is not None:
            raise self._error

    def _put(self, cmd):
        # bounded put that can't deadlock against a dead comm thread:
        # re-check the error flag while waiting for queue space
        while True:
            self._check()
            try:
                self._cmds.put(cmd, timeout=0.5)
                return
            except queue.Full:
                continue

    def push(self, delta: List[np.ndarray]):
        self._put(("push", delta))

    def push_frame(self, arrays: List[np.ndarray], kind: int):
        """Queue an already-built update frame (compressed pushes: the
        worker's ErrorFeedback quantized once; no re-quantization on
        this thread)."""
        self._put(("push_frame", (arrays, kind)))

    def request_pull(self):
        self._put(("pull", None))

    def take_latest(self, block: bool = False,
                    timeout: Optional[float] = None
                    ) -> Optional[tuple]:
        """Consume the freshest pulled weights as ``(weights,
        pushes_reflected)``, or None if no pull completed since the last
        take. ``pushes_reflected`` counts this worker's own pushes the
        snapshot is guaranteed to include."""
        if block:
            self._fresh.wait(timeout)
        self._check()
        with self._lock:
            snapshot, self._latest = self._latest, None
            self._fresh.clear()
        return snapshot

    def close(self):
        """Drain queued pushes, stop the thread, re-raise any error."""
        if self._error is None:
            self._put(None)
        self._thread.join()
        self._check()


class _PipelinedPusher:
    """One-slot pipelined delta pusher for the reference-parity loops.

    The push for batch/epoch *k* runs on a background thread over its
    OWN cloned client (own persistent connection), overlapping the pull
    and gradient computation for *k+1*. ``submit`` first waits for the
    previous in-flight push — at most ONE push is outstanding, so a
    pull can miss at most the single racing push (bounded staleness 1,
    on top of what asynchronous SGD already tolerates). A push error is
    parked and re-raised at the next sync point (``submit``/``drain``),
    so the worker fails exactly as the blocking loop would and the
    supervisor's crash/restart semantics are unchanged.
    """

    def __init__(self, client: BaseParameterClient):
        self.client = client.clone()
        self._owns_client = self.client is not client
        self._slot: "queue.Queue" = queue.Queue(maxsize=1)
        self._idle = threading.Event()
        self._idle.set()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="elephas-tpu-ps-pipeline")
        self._thread.start()

    def _run(self):
        while True:
            item = self._slot.get()
            if item is None:
                return
            arrays, kind = item
            try:
                try:
                    self.client.push_frame(arrays, kind)
                except NotImplementedError:
                    # in-memory doubles implement only update_parameters;
                    # an uncompressed frame IS the delta list
                    self.client.update_parameters(arrays)
            except BaseException as err:  # noqa: BLE001 — re-raised at sync
                self._error = err
            finally:
                self._idle.set()

    def _sync(self):
        """Wait for the in-flight push; re-raise its error exactly once."""
        self._idle.wait()
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, arrays: List[np.ndarray], kind: int):
        """Hand a push to the background thread after the previous one
        lands — the caller blocks only when the wire is slower than
        compute (the same back-pressure the blocking loop has)."""
        self._sync()
        self._idle.clear()
        self._slot.put((arrays, kind))

    def drain(self):
        """Block until the wire is quiet (epoch boundaries, final flush)."""
        self._sync()

    def close(self):
        """Flush the in-flight push, stop the thread, release the
        cloned connection. Re-raises a parked error unless a prior sync
        already surfaced it (a finally-path close must not mask the
        loop's own exception with a second raise of the same error)."""
        try:
            self._sync()
        finally:
            self._slot.put(None)
            self._thread.join()
            if self._owns_client:
                self.client.close()


class AsyncWorker:
    """Asynchronous worker: exchanges weight deltas with a parameter server
    at epoch or batch frequency (parity: ``elephas/worker.py:52-131``).

    :param overlap: run the batch-frequency loop with a background RPC
        thread and device-resident parameters (throughput configuration)
    :param accum_batches: accumulate the weight delta on device for this
        many steps before pushing (1 = push every batch, as the
        reference does)
    :param pipeline: double-buffer pushes in the reference-parity loops:
        the delta push for batch/epoch *k* runs on a background thread
        (own connection) while *k+1* computes — one in-flight push max,
        staleness bounded at 1, push errors re-raised at the next sync
        point. Subsumed by ``overlap``/``accum_batches`` only at BATCH
        frequency (where the overlapped communicator runs and already
        pipelines); epoch-frequency fits keep the pusher regardless.
    :param epoch_event: optional ``(epoch_idx, mean_loss_or_None)`` hook
        fired after each local epoch — the driver aggregates these into
        real per-epoch callbacks across workers
    :param should_stop: optional predicate polled at epoch boundaries;
        True ends training early (EarlyStopping reaching into the
        workers)
    """

    def __init__(self, json_config: str, parameters: List[np.ndarray],
                 client: Union[BaseParameterClient, str],
                 train_config: Dict[str, Any], frequency: str,
                 master_optimizer, master_loss, master_metrics,
                 custom_objects: Optional[Dict] = None, port: int = 4000,
                 overlap: bool = False, accum_batches: int = 1,
                 pipeline: bool = False,
                 epoch_event=None, should_stop=None,
                 compute_dtype: Optional[str] = None, device=None):
        if isinstance(client, BaseParameterClient):
            # own transport state per worker: N workers must not
            # serialize their RPCs over the driver's one connection
            self.client = client.clone()
        else:
            self.client = BaseParameterClient.get_client(client, port)
        self.json = json_config
        self.parameters = parameters
        self.train_config = dict(train_config)
        self.frequency = frequency
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects or {}
        self.compute_dtype = compute_dtype
        self.overlap = overlap
        self.accum_batches = max(1, int(accum_batches))
        self.pipeline = bool(pipeline)
        self._pusher: Optional[_PipelinedPusher] = None
        self.epoch_event = epoch_event
        self.should_stop = should_stop or (lambda: False)
        #: the JAX device this worker's compute is pinned to (None =
        #: process default). On a multi-chip host the driver assigns
        #: workers round-robin over local devices so N async workers
        #: drive N chips instead of contending for chip 0.
        self.device = device
        self.model = None
        # EF-SGD residual carrier when the client compresses pushes:
        # per-worker state, so each worker corrects its own rounding
        if getattr(self.client, "compression", None):
            from .utils.delta_compression import ErrorFeedback

            self._ef = ErrorFeedback()
        else:
            self._ef = None

    def _push(self, delta):
        """Push a delta, routing through error feedback when the wire
        quantizes (keeps the server-side sum unbiased). The EF preview
        frame IS the wire frame — one quantization pass per push. With
        ``pipeline=True`` the frame is handed to the background pusher
        instead of blocking the loop (EF still quantizes HERE, on the
        compute thread, so residuals stay ordered)."""
        if self._ef is not None:
            self._ef.apply(delta)
            if self._pusher is not None:
                self._pusher.submit(self._ef.last_frame, _KIND_DELTA_Q8)
            else:
                self.client.push_frame(self._ef.last_frame, _KIND_DELTA_Q8)
        elif self._pusher is not None:
            # uncompressed frame (compression implies EF above)
            self._pusher.submit(delta, _KIND_DELTA)
        else:
            self.client.update_parameters(delta)

    def _emit(self, epoch: int, loss: Optional[float]):
        if self.epoch_event is not None:
            self.epoch_event(epoch, loss)

    def train(self, x_train: np.ndarray, y_train: np.ndarray):
        if x_train.size == 0:
            return
        if self.device is not None:
            # jax.default_device is a thread-local config context: every
            # array this worker thread creates and every step it compiles
            # lands on ITS chip, concurrently with its siblings on theirs
            with jax.default_device(self.device):
                return self._train_pinned(x_train, y_train)
        return self._train_pinned(x_train, y_train)

    def _train_pinned(self, x_train: np.ndarray, y_train: np.ndarray):
        fault_site("worker.train")  # chaos hook: crash/stall a worker
        # the overlapped schedule's communicator already pipelines, but
        # it only runs for BATCH frequency — epoch-frequency fits keep
        # the pusher even when overlap/accum flags are set, otherwise
        # ps_pipeline would be silently dropped there
        overlapped = (self.frequency == "batch"
                      and (self.overlap or self.accum_batches > 1))
        if self.pipeline and not overlapped:
            # this pusher is the lightweight upgrade for the
            # reference-parity loops
            self._pusher = _PipelinedPusher(self.client)
            try:
                return self._train_loops(x_train, y_train)
            finally:
                pusher, self._pusher = self._pusher, None
                pusher.close()
        return self._train_loops(x_train, y_train)

    def _train_loops(self, x_train: np.ndarray, y_train: np.ndarray):
        self.model = model_from_json(self.json, self.custom_objects)
        self.model.compile(optimizer=deserialize_optimizer(self.master_optimizer),
                           loss=self.master_loss, metrics=self.master_metrics,
                           custom_objects=self.custom_objects,
                           compute_dtype=self.compute_dtype)
        self.model.set_weights(self.parameters)

        train_config = dict(self.train_config)
        epochs = train_config.get("epochs", 1)
        batch_size = train_config.get("batch_size", 32)
        nb_train_sample = x_train.shape[0]
        nb_batch = int(np.ceil(nb_train_sample / float(batch_size)))
        batches = [(i * batch_size, min(nb_train_sample, (i + 1) * batch_size))
                   for i in range(nb_batch)]

        if self.frequency == "epoch":
            for epoch in range(epochs):
                if self.should_stop():
                    break
                fault_site("worker.epoch")  # chaos hook: die mid-fit
                weights_before = self.client.get_parameters()
                self.model.set_weights(weights_before)
                history = None
                if x_train.shape[0] > batch_size:
                    per_epoch = dict(train_config)
                    per_epoch["epochs"] = 1
                    history = self.model.fit(x_train, y_train, **per_epoch)
                weights_after = self.model.get_weights()
                self._push(subtract_params(weights_before, weights_after))
                loss = (history.history["loss"][-1]
                        if history and history.history.get("loss") else None)
                self._emit(epoch, loss)
        elif self.frequency == "batch":
            if self.overlap or self.accum_batches > 1:
                if x_train.shape[0] > batch_size:
                    self._train_batches_overlapped(x_train, y_train, epochs,
                                                   batches)
                else:
                    # too small to train, but still a participant: keep
                    # the driver's epoch aggregation complete
                    for epoch in range(epochs):
                        if self.should_stop():
                            break
                        self._emit(epoch, None)
                return
            for epoch in range(epochs):
                if self.should_stop():
                    break
                fault_site("worker.epoch")  # chaos hook: die mid-fit
                losses = []
                if x_train.shape[0] > batch_size:
                    for batch_start, batch_end in batches:
                        weights_before = self.client.get_parameters()
                        self.model.set_weights(weights_before)
                        vals = self.model.train_on_batch(
                            x_train[batch_start:batch_end],
                            y_train[batch_start:batch_end])
                        losses.append(vals[0] if isinstance(vals, list)
                                      else float(vals))
                        weights_after = self.model.get_weights()
                        self._push(
                            subtract_params(weights_before, weights_after))
                self._emit(epoch,
                           float(np.mean(losses)) if losses else None)
        else:
            raise ValueError(
                "frequency parameter can be `epoch` or `batch`, got {}".format(
                    self.frequency))

    def _train_batches_overlapped(self, x_train, y_train, epochs, batches):
        """Batch-frequency loop, TPU schedule: device-resident params, one
        jit compile, delta accumulation over ``accum_batches`` steps, and
        RPCs on a background thread (double-buffered weights).

        Semantics vs the reference loop: the pulled global weights a
        window trains from may be one push older than the server's very
        latest (the price of not blocking compute on the pull) — a
        staleness already inherent to asynchronous SGD.
        """
        import jax.numpy as jnp

        model = self.model
        entries = model._weight_entries()

        def as_params(weights):
            new = {ln: dict(lp) for ln, lp in model.params.items()}
            for (ln, pn), w in zip(entries, weights):
                new[ln][pn] = jnp.asarray(w, dtype=new[ln][pn].dtype)
            return new

        def as_weights(params):
            # the one device->host transfer per window
            return [np.asarray(params[ln][pn]) for ln, pn in entries]

        x_all = model._prepare_x(x_train)
        y_all = model._prepare_y(y_train)

        comm = _AsyncCommunicator(self.client)
        try:
            comm.request_pull()
            base_weights, _ = comm.take_latest(block=True)
            model.params = as_params(base_weights)
            trainable, state = model._split_params(model.params)
            opt_state = model._tx.init(trainable)
            step = model._get_jitted("train")
            base = model._merge_params(trainable, state)

            window = 0
            pushes_issued = 0
            pending: Dict[int, List[np.ndarray]] = {}  # seq -> host delta
            for epoch in range(epochs):
                if self.should_stop():
                    break
                fault_site("worker.epoch")  # chaos hook: die mid-fit
                epoch_losses = []
                batch_iter = prefetch_to_device(
                    ((x_all[s:e], y_all[s:e]) for s, e in batches), size=2)
                for xb, yb in batch_iter:
                    trainable, state, opt_state, loss_val, _ = step(
                        trainable, state, opt_state, model._next_key(),
                        xb, yb)
                    epoch_losses.append(loss_val)  # device scalar, no sync
                    window += 1
                    if window < self.accum_batches:
                        continue
                    window = 0
                    current = model._merge_params(trainable, state)
                    delta = jax.tree_util.tree_map(lambda a, b: a - b,
                                                   base, current)
                    host_delta = as_weights(delta)
                    if self._ef is not None:
                        # pending must hold what the server APPLIES (the
                        # dequantized push), or the snapshot correction
                        # drifts by the quantization error; the EF frame
                        # ships as-is (one quantization per push)
                        self._ef.apply(host_delta)
                        comm.push_frame(self._ef.last_frame,
                                        _KIND_DELTA_Q8)
                        applied = self._ef.last_on_wire
                    else:
                        comm.push(host_delta)
                        applied = host_delta
                    pushes_issued += 1
                    pending[pushes_issued] = applied
                    comm.request_pull()  # FIFO: pull sees our push applied
                    fresh = comm.take_latest(block=False)
                    if fresh is not None:
                        # adopt the snapshot (peer updates included),
                        # corrected by our own pushes it can't reflect
                        # yet — the server applies them regardless, so
                        # subtracting locally keeps our trajectory intact
                        # (1-worker case: adopted == current, exactly)
                        snap_weights, reflected = fresh
                        pending = {s: d for s, d in pending.items()
                                   if s > reflected}
                        adopted = [np.array(w) for w in snap_weights]
                        for d in pending.values():
                            adopted = [a - dd for a, dd in zip(adopted, d)]
                        model.params = as_params(adopted)
                        trainable, state = model._split_params(model.params)
                        base = model._merge_params(trainable, state)
                    else:
                        # pull not back yet: keep training from local state
                        base = current
                # one host sync per epoch: the mean loss for the driver's
                # aggregated epoch_end logs
                self._emit(epoch, float(np.mean([float(l)
                                                 for l in epoch_losses])))
            # flush a partial window so no training is lost
            if window:
                current = model._merge_params(trainable, state)
                delta = jax.tree_util.tree_map(lambda a, b: a - b,
                                               base, current)
                host_delta = as_weights(delta)
                if self._ef is not None:
                    self._ef.apply(host_delta)
                    comm.push_frame(self._ef.last_frame, _KIND_DELTA_Q8)
                else:
                    comm.push(host_delta)
        finally:
            comm.close()
        model.params = model._merge_params(trainable, state)
