"""Per-partition training workers.

The reference ships these closures to Spark executors via
``rdd.mapPartitions`` (``elephas/worker.py:11-131``). Here workers are
driven by the single-controller :class:`~elephas_tpu.tpu_model.TPUModel`:

- Synchronous training normally runs *all* workers inside one jitted,
  mesh-sharded program (:class:`~elephas_tpu.parallel.SyncAverageTrainer`);
  :class:`SyncWorker` is the per-partition scalar implementation of the
  same semantics, used as a reference/fallback path and for tests.
- :class:`AsyncWorker` mirrors the reference's asynchronous executor loop
  exactly: pull global weights from the parameter server, train locally
  for one epoch (or one batch), push the weight delta
  (``elephas/worker.py:76-131``). Workers run as coordinator-host threads,
  each driving jit-compiled local steps.
"""
from typing import Any, Dict, List, Optional, Union

import numpy as np

from .models import deserialize_optimizer, model_from_json
from .parameter import BaseParameterClient
from .utils.functional_utils import subtract_params


class SyncWorker:
    """Train a full local model copy on one partition; return the weight
    delta and training history (parity: ``elephas/worker.py:11-49``)."""

    def __init__(self, json_config: str, parameters: List[np.ndarray],
                 train_config: Dict[str, Any], master_optimizer,
                 master_loss, master_metrics,
                 custom_objects: Optional[Dict] = None):
        self.json = json_config
        self.parameters = parameters
        self.train_config = dict(train_config)
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects or {}
        self.model = None

    def train(self, x_train: np.ndarray, y_train: np.ndarray):
        """Returns ``[delta, history_dict_or_None]``."""
        history = None
        self.model = model_from_json(self.json, self.custom_objects)
        self.model.compile(optimizer=deserialize_optimizer(self.master_optimizer),
                           loss=self.master_loss, metrics=self.master_metrics,
                           custom_objects=self.custom_objects)
        self.model.set_weights(self.parameters)

        weights_before = self.model.get_weights()
        batch_size = self.train_config.get("batch_size", 32)
        if x_train.shape[0] > batch_size:
            history = self.model.fit(x_train, y_train, **self.train_config)
        weights_after = self.model.get_weights()
        deltas = subtract_params(weights_before, weights_after)
        return [deltas, history.history if history else None]


class AsyncWorker:
    """Asynchronous worker: exchanges weight deltas with a parameter server
    at epoch or batch frequency (parity: ``elephas/worker.py:52-131``)."""

    def __init__(self, json_config: str, parameters: List[np.ndarray],
                 client: Union[BaseParameterClient, str],
                 train_config: Dict[str, Any], frequency: str,
                 master_optimizer, master_loss, master_metrics,
                 custom_objects: Optional[Dict] = None, port: int = 4000):
        if isinstance(client, BaseParameterClient):
            self.client = client
        else:
            self.client = BaseParameterClient.get_client(client, port)
        self.json = json_config
        self.parameters = parameters
        self.train_config = dict(train_config)
        self.frequency = frequency
        self.master_optimizer = master_optimizer
        self.master_loss = master_loss
        self.master_metrics = master_metrics
        self.custom_objects = custom_objects or {}
        self.model = None

    def train(self, x_train: np.ndarray, y_train: np.ndarray):
        if x_train.size == 0:
            return

        self.model = model_from_json(self.json, self.custom_objects)
        self.model.compile(optimizer=deserialize_optimizer(self.master_optimizer),
                           loss=self.master_loss, metrics=self.master_metrics,
                           custom_objects=self.custom_objects)
        self.model.set_weights(self.parameters)

        train_config = dict(self.train_config)
        epochs = train_config.get("epochs", 1)
        batch_size = train_config.get("batch_size", 32)
        nb_train_sample = x_train.shape[0]
        nb_batch = int(np.ceil(nb_train_sample / float(batch_size)))
        batches = [(i * batch_size, min(nb_train_sample, (i + 1) * batch_size))
                   for i in range(nb_batch)]

        if self.frequency == "epoch":
            for _ in range(epochs):
                weights_before = self.client.get_parameters()
                self.model.set_weights(weights_before)
                if x_train.shape[0] > batch_size:
                    per_epoch = dict(train_config)
                    per_epoch["epochs"] = 1
                    self.model.fit(x_train, y_train, **per_epoch)
                weights_after = self.model.get_weights()
                self.client.update_parameters(
                    subtract_params(weights_before, weights_after))
        elif self.frequency == "batch":
            for _ in range(epochs):
                if x_train.shape[0] > batch_size:
                    for batch_start, batch_end in batches:
                        weights_before = self.client.get_parameters()
                        self.model.set_weights(weights_before)
                        self.model.train_on_batch(
                            x_train[batch_start:batch_end],
                            y_train[batch_start:batch_end])
                        weights_after = self.model.get_weights()
                        self.client.update_parameters(
                            subtract_params(weights_before, weights_after))
        else:
            raise ValueError(
                "frequency parameter can be `epoch` or `batch`, got {}".format(
                    self.frequency))
