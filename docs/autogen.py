"""Introspection-based documentation generator.

Walks the public API and renders one markdown page per module from
docstrings and signatures (capability mirror of the reference's
``docs/autogen.py`` mkdocs generator). Output goes to ``docs/sources/``;
``docs/mkdocs.yml`` holds the nav.

Usage: ``python docs/autogen.py``
"""
import inspect
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

PAGES = [
    ("TPUModel", "elephas_tpu.tpu_model",
     ["TPUModel", "TPUMatrixModel", "load_tpu_model"]),
    ("Models", "elephas_tpu.models.core",
     ["Sequential", "Model", "BaseModel", "model_from_json"]),
    ("Layers", "elephas_tpu.models.layers",
     ["Dense", "Activation", "Dropout", "Flatten", "Reshape", "Conv2D",
      "MaxPooling2D", "AveragePooling2D", "GlobalAveragePooling2D",
      "Embedding", "LSTM", "GRU", "LayerNormalization",
      "BatchNormalization", "Add", "Multiply", "Concatenate", "Input"]),
    ("Optimizers", "elephas_tpu.models.optimizers",
     ["SGD", "Adam", "AdamW", "RMSprop", "Adagrad", "Adadelta", "Nadam",
      "Adafactor", "Lion", "LAMB"]),
    ("LR schedules", "elephas_tpu.models.schedules",
     ["ExponentialDecay", "CosineDecay", "PiecewiseConstantDecay",
      "WarmupCosine"]),
    ("Workers", "elephas_tpu.worker", ["SyncWorker", "AsyncWorker"]),
    ("Worker supervision", "elephas_tpu.parallel.supervisor",
     ["WorkerSupervisor", "SupervisorReport", "QuorumLostError"]),
    ("Fault injection", "elephas_tpu.utils.faults",
     ["FaultPlan", "FaultEvent", "fault_site", "install_plan",
      "clear_plan", "active_plan", "InjectedFault"]),
    ("Parameter servers", "elephas_tpu.parameter.server",
     ["BaseParameterServer", "HttpServer", "SocketServer"]),
    ("Parameter clients", "elephas_tpu.parameter.client",
     ["BaseParameterClient", "HttpClient", "SocketClient"]),
    ("Parameter-plane sharding", "elephas_tpu.parameter.sharding",
     ["ShardPlan", "ShardedServerGroup", "ShardedParameterClient",
      "TornPushError", "CommitAbortedError", "GenerationMismatchError"]),
    ("Parameter-plane replication", "elephas_tpu.parameter.replication",
     ["ShardReplicator", "ShardStandby"]),
    ("Parallel trainers", "elephas_tpu.parallel.sync_trainer",
     ["SyncAverageTrainer", "SyncStepTrainer", "build_sharded_predict",
      "build_sharded_evaluate"]),
    ("Mesh utilities", "elephas_tpu.parallel.mesh",
     ["worker_mesh", "data_mesh", "make_mesh", "hybrid_mesh",
      "shard_leading", "replicate"]),
    ("Multi-host", "elephas_tpu.parallel.multihost",
     ["initialize_multihost", "is_coordinator", "host_local_slice",
      "global_batch_from_host_data"]),
    ("ML pipeline", "elephas_tpu.ml.pipeline",
     ["Estimator", "Transformer", "load_ml_estimator", "load_ml_transformer"]),
    ("DataFrame adapters", "elephas_tpu.ml.adapter",
     ["to_data_frame", "from_data_frame", "df_to_dataset"]),
    ("Datasets", "elephas_tpu.data.dataset", ["Dataset"]),
    ("Out-of-core sources", "elephas_tpu.data.sources",
     ["ColumnSource", "ConcatSource", "NpySource", "ParquetSource",
      "SourceView"]),
    ("Dataset utilities", "elephas_tpu.utils.dataset_utils",
     ["to_dataset", "to_labeled_points", "from_labeled_points",
      "lp_to_dataset", "encode_label"]),
    ("Linalg", "elephas_tpu.mllib.linalg",
     ["DenseVector", "DenseMatrix", "LabeledPoint", "Vectors", "Matrices"]),
    ("Attention ops", "elephas_tpu.ops.attention",
     ["attention", "blockwise_attention"]),
    ("Flash attention (Pallas)", "elephas_tpu.ops.pallas_attention",
     ["flash_attention"]),
    ("Ring attention", "elephas_tpu.ops.ring_attention",
     ["ring_attention", "ring_flash_attention", "ring_attention_sharded"]),
    ("Transformer", "elephas_tpu.models.transformer",
     ["TransformerConfig", "init_params", "param_specs",
      "fsdp_param_specs", "zero_opt_specs", "abstract_params", "forward",
      "forward_with_aux", "lm_loss", "make_train_step", "shard_params",
      "select_moe_dispatch", "init_kv_cache", "decode_step", "generate",
      "beam_search"]),
    ("TransformerModel", "elephas_tpu.models.transformer_model",
     ["TransformerModel"]),
    ("LoRA fine-tuning", "elephas_tpu.models.lora",
     ["init_lora_params", "merge_lora", "make_lora_train_step",
      "lora_param_count"]),
    ("Encoder-decoder (seq2seq)", "elephas_tpu.models.encdec",
     ["EncDecConfig", "init_params", "param_specs", "encode",
      "decode_logits", "seq2seq_loss", "make_train_step", "greedy_decode",
      "shard_params"]),
    ("BERT encoder (MLM)", "elephas_tpu.models.bert",
     ["BertConfig", "init_params", "param_specs", "encode", "pool",
      "mask_tokens", "mlm_loss", "make_mlm_train_step", "shard_params"]),
    ("Vision Transformer", "elephas_tpu.models.vit",
     ["ViTConfig", "init_params", "param_specs", "forward", "vit_loss",
      "make_train_step", "shard_params"]),
    ("Pipeline parallelism", "elephas_tpu.parallel.pipeline",
     ["make_pipeline_fn", "stack_stage_params", "split_transformer_stages",
      "merge_transformer_stages", "shard_pipelined_params",
      "make_pipelined_lm_loss", "make_pipelined_train_step"]),
    ("Callbacks", "elephas_tpu.models.callbacks",
     ["Callback", "EarlyStopping", "ModelCheckpoint", "LambdaCallback"]),
    ("Quantized serving (int8)", "elephas_tpu.models.quantization",
     ["QTensor", "quantize_weight", "quantize_lm_params",
      "dequantize_lm_params"]),
    ("Speculative decoding", "elephas_tpu.models.speculative",
     ["speculative_generate", "speculative_round",
      "speculative_round_paged"]),
    ("Draft distillation", "elephas_tpu.models.distill",
     ["distill_loss", "make_distill_step"]),
    ("Continuous batching", "elephas_tpu.serving_engine",
     ["DecodeEngine", "QueueFullError", "DeadlineExceededError"]),
    ("Multi-tenant QoS", "elephas_tpu.serving_qos",
     ["TenantQoS", "FairQueue", "QueuedRequest"]),
    ("HTTP serving", "elephas_tpu.serving_http", ["ServingServer"]),
    ("Serving fleet API", "elephas_tpu.fleet",
     ["FleetRouter", "ReplicaMembership", "HashRing", "ReplicaPool",
      "ReplicaSupervisor", "RestartPolicy",
      "RetryPolicy", "RetryBudget", "CircuitBreaker",
      "FleetAutoscaler", "TierPolicy", "ReplicaPoolTier",
      "DisaggDecodeTier", "DisaggPrefillTier"]),
    ("Disaggregated serving API", "elephas_tpu.disagg",
     ["DisaggEngine", "DisaggPool", "PrefillWorker", "PrefillJob",
      "KVReceiver", "KVShipper", "encode_kv_frame", "decode_kv_frame"]),
    ("Live weight plane API", "elephas_tpu.weightsync",
     ["WeightSubscriber", "CanaryController"]),
    ("SSM serving", "elephas_tpu.ssm_engine", ["SSMEngine"]),
    ("Paged KV cache", "elephas_tpu.models.paged_decode",
     ["init_paged_pool", "decode_step_paged", "decode_block_paged",
      "install_row_paged", "gather_blocks_to_row", "export_kv_blocks",
      "import_kv_blocks"]),
    ("KV block cache", "elephas_tpu.models.block_cache",
     ["BlockCache", "BlockEntry", "chain_keys"]),
    ("Tiered KV API", "elephas_tpu.kvtier",
     ["TieredSpill", "HostTier", "StorageTier", "SpilledBlock",
      "SessionStore", "encode_payload", "decode_payload"]),
    ("SSMModel", "elephas_tpu.models.ssm_model", ["SSMModel"]),
    ("Selective SSM (Mamba-style)", "elephas_tpu.models.ssm",
     ["SSMConfig", "init_ssm_params", "ssm_forward", "ssm_lm_loss",
      "make_ssm_train_step", "init_ssm_state", "ssm_decode_step",
      "ssm_generate"]),
    ("Checkpointing", "elephas_tpu.utils.checkpoint", ["CheckpointManager"]),
    ("Object storage", "elephas_tpu.utils.storage",
     ["ObjectStore", "CliObjectStore", "LocalMirrorStore", "register_store",
      "get_store"]),
    ("Native acceleration", "elephas_tpu.utils.native",
     ["build", "available", "NativeBatchLoader", "batch_iterator"]),
    ("Text utilities", "elephas_tpu.utils.text", ["ByteTokenizer"]),
    ("Serving", "elephas_tpu.serving", ["TextGenerator"]),
    ("Step timing", "elephas_tpu.utils.tracing",
     ["StepTimer", "profiler_trace", "annotate"]),
    ("Observability metrics API", "elephas_tpu.obs.metrics",
     ["MetricsRegistry", "Counter", "Gauge", "Histogram",
      "default_registry", "percentile"]),
    ("Trace spans API", "elephas_tpu.obs.trace",
     ["span", "span_if_counted", "record_span", "recent_slow_spans",
      "clear_slow_spans", "set_slow_span_threshold"]),
    ("Trace context API", "elephas_tpu.obs.context",
     ["TraceContext", "current_context", "current_trace_id", "new_root",
      "parse_traceparent", "set_context", "reset_context",
      "use_context"]),
    ("Event log API", "elephas_tpu.obs.events",
     ["EventLog", "FlightRecorder", "default_event_log", "emit",
      "recent_events", "clear_events"]),
    ("Loop profiler API", "elephas_tpu.obs.profiler",
     ["LoopProfiler"]),
    ("SLO plane API", "elephas_tpu.obs.slo",
     ["SLOObjective", "SLOTracker"]),
    ("Engine watchdog API", "elephas_tpu.obs.watchdog",
     ["EngineWatchdog"]),
    ("Wire codec", "elephas_tpu.utils.tensor_codec",
     ["encode_tensors", "decode_tensors", "encode", "decode"]),
    ("Delta compression", "elephas_tpu.utils.delta_compression",
     ["quantize_delta", "dequantize_delta", "ErrorFeedback"]),
    ("Input prefetch", "elephas_tpu.utils.prefetch",
     ["prefetch_to_device"]),
]


def _doc(obj) -> str:
    return inspect.getdoc(obj) or "*(no docstring)*"


def _signature(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def render_page(title: str, module_name: str, names) -> str:
    import importlib

    module = importlib.import_module(module_name)
    lines = [f"# {title}", "", f"`{module_name}`", ""]
    if module.__doc__:
        lines += [inspect.cleandoc(module.__doc__), ""]
    for name in names:
        obj = getattr(module, name)
        lines.append(f"## {name}")
        lines.append("")
        if inspect.isclass(obj):
            lines.append(f"```python\n{name}{_signature(obj.__init__)}\n```")
            lines += ["", _doc(obj), ""]
            for meth_name, meth in sorted(vars(obj).items()):
                if meth_name.startswith("_") or not callable(meth):
                    continue
                lines.append(f"### {name}.{meth_name}")
                lines.append(f"```python\n{meth_name}{_signature(meth)}\n```")
                lines += ["", _doc(meth), ""]
        elif callable(obj):
            lines.append(f"```python\n{name}{_signature(obj)}\n```")
            lines += ["", _doc(obj), ""]
        else:
            lines += [_doc(obj), ""]
    return "\n".join(lines)


def main(out_dir: str = None):
    out = Path(out_dir) if out_dir else ROOT / "docs" / "sources"
    out.mkdir(parents=True, exist_ok=True)
    nav = []
    import re

    for title, module_name, names in PAGES:
        slug = re.sub(r"[^a-z0-9]+", "-",
                      title.lower()).strip("-")
        (out / f"{slug}.md").write_text(render_page(title, module_name, names))
        nav.append((title, f"{slug}.md"))
        print(f"wrote {slug}.md")
    mkdocs = ["site_name: elephas_tpu", "nav:", "  - Home: index.md",
              "  - Scaling guide: scaling-guide.md",
              "  - Serving guide: serving-guide.md",
              "  - Serving operations: serving-operations.md",
              "  - Serving fleet: serving-fleet.md",
              "  - Disaggregated serving: disaggregated-serving.md",
              "  - Live weights: live-weights.md",
              "  - Speculative serving: speculative-serving.md",
              "  - Tiered KV: tiered-kv.md",
              "  - Fault tolerance: fault-tolerance.md",
              "  - Observability: observability.md",
              "  - Distributed tracing: tracing.md"]
    mkdocs += [f"  - {title}: {page}" for title, page in nav]
    (ROOT / "docs" / "mkdocs.yml").write_text("\n".join(mkdocs) + "\n")
    index = ROOT / "README.md"
    (out / "index.md").write_text(index.read_text())
    print("wrote mkdocs.yml and index.md")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
