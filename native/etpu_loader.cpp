// ETPU native batch loader: background gather + prefetch for training.
//
// The Python fit loop's per-batch host work is a fancy-index gather
// (x[order[i:i+b]], y[...]) that runs serially with device dispatch. This
// loader moves the gather into a producer thread over a ring of
// pre-allocated batch buffers, so batch N+1 (and N+2, ...) assembles while
// the device runs batch N.
//
// Protocol (ctypes, see elephas_tpu/utils/native.py):
//   h = etpu_loader_create(ncols, col_ptrs, row_bytes, nrows, order,
//                          batch_size, depth)
//   n = etpu_loader_next(h, out_ptrs)   // rows in batch; 0 = epoch done
//                                       // blocks until the slot is filled;
//                                       // implicitly recycles the slot
//                                       // returned by the previous call
//   etpu_loader_destroy(h)
//
// The column base pointers and the order array are BORROWED for the
// loader's lifetime — the Python side must keep the arrays alive and
// unchanged until destroy. Buffers returned by next() stay valid until the
// following next()/destroy call.
//
// Build: native/build.sh (g++ -O3 -shared -fPIC -pthread).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

struct EtpuLoader {
    int ncols;
    std::vector<const uint8_t*> cols;
    std::vector<uint64_t> row_bytes;
    uint64_t nrows;
    std::vector<uint64_t> order;
    uint64_t batch_size;
    uint64_t nbatches;
    int depth;

    // ring of depth slots, each holding ncols buffers
    std::vector<std::vector<std::vector<uint8_t>>> slots;
    std::vector<int64_t> slot_batch;  // batch index held, -1 = free

    std::mutex mu;
    std::condition_variable filled_cv;
    std::condition_variable free_cv;
    uint64_t next_serve = 0;   // batch the consumer will take next
    int64_t served_slot = -1;  // slot handed out by the previous next()
    bool stop = false;
    std::thread producer;
};

static void producer_loop(EtpuLoader* L) {
    for (uint64_t b = 0; b < L->nbatches; ++b) {
        int slot = (int)(b % (uint64_t)L->depth);
        {
            std::unique_lock<std::mutex> lk(L->mu);
            L->free_cv.wait(lk, [&] {
                return L->stop || L->slot_batch[slot] < 0;
            });
            if (L->stop) return;
        }
        uint64_t lo = b * L->batch_size;
        uint64_t hi = lo + L->batch_size;
        if (hi > L->nrows) hi = L->nrows;
        uint64_t rows = hi - lo;
        for (int c = 0; c < L->ncols; ++c) {
            uint64_t rb = L->row_bytes[c];
            uint8_t* dst = L->slots[slot][c].data();
            const uint8_t* src = L->cols[c];
            for (uint64_t r = 0; r < rows; ++r) {
                std::memcpy(dst + r * rb, src + L->order[lo + r] * rb, rb);
            }
        }
        {
            std::lock_guard<std::mutex> lk(L->mu);
            L->slot_batch[slot] = (int64_t)b;
        }
        L->filled_cv.notify_one();
    }
}

void* etpu_loader_create(int32_t ncols, const void** col_ptrs,
                         const uint64_t* row_bytes, uint64_t nrows,
                         const uint64_t* order, uint64_t batch_size,
                         int32_t depth) {
    if (ncols <= 0 || nrows == 0 || batch_size == 0 || depth <= 0)
        return nullptr;
    EtpuLoader* L = new EtpuLoader();
    L->ncols = ncols;
    L->nrows = nrows;
    L->batch_size = batch_size;
    L->nbatches = (nrows + batch_size - 1) / batch_size;
    L->depth = depth;
    // slot buffers only ever hold min(batch_size, nrows) rows — don't let
    // an oversized batch_size drive a huge (or fatal) allocation
    uint64_t slot_rows = batch_size < nrows ? batch_size : nrows;
    try {
        L->cols.resize(ncols);
        L->row_bytes.resize(ncols);
        for (int c = 0; c < ncols; ++c) {
            L->cols[c] = (const uint8_t*)col_ptrs[c];
            L->row_bytes[c] = row_bytes[c];
        }
        L->order.assign(order, order + nrows);
        L->slots.resize(depth);
        L->slot_batch.assign(depth, -1);
        for (int s = 0; s < depth; ++s) {
            L->slots[s].resize(ncols);
            for (int c = 0; c < ncols; ++c)
                L->slots[s][c].resize(slot_rows * row_bytes[c]);
        }
    } catch (const std::bad_alloc&) {
        delete L;  // surface as a create failure, not std::terminate
        return nullptr;
    }
    L->producer = std::thread(producer_loop, L);
    return L;
}

int64_t etpu_loader_next(void* handle, void** out_ptrs) {
    EtpuLoader* L = (EtpuLoader*)handle;
    if (!L) return -1;
    std::unique_lock<std::mutex> lk(L->mu);
    // recycle the slot from the previous call
    if (L->served_slot >= 0) {
        L->slot_batch[L->served_slot] = -1;
        L->served_slot = -1;
        L->free_cv.notify_one();
    }
    if (L->next_serve >= L->nbatches) return 0;  // epoch exhausted
    int slot = (int)(L->next_serve % (uint64_t)L->depth);
    L->filled_cv.wait(lk, [&] {
        return L->slot_batch[slot] == (int64_t)L->next_serve;
    });
    for (int c = 0; c < L->ncols; ++c)
        out_ptrs[c] = L->slots[slot][c].data();
    uint64_t lo = L->next_serve * L->batch_size;
    uint64_t hi = lo + L->batch_size;
    if (hi > L->nrows) hi = L->nrows;
    L->served_slot = slot;
    L->next_serve += 1;
    return (int64_t)(hi - lo);
}

void etpu_loader_destroy(void* handle) {
    EtpuLoader* L = (EtpuLoader*)handle;
    if (!L) return;
    {
        std::lock_guard<std::mutex> lk(L->mu);
        L->stop = true;
    }
    L->free_cv.notify_all();
    L->filled_cv.notify_all();
    if (L->producer.joinable()) L->producer.join();
    delete L;
}

}  // extern "C"
