#!/bin/sh
# Build the native ETPU library (wire codec + batch loader) in place.
# Optional $1: output filename (default libetpu.so) — the Python build()
# helper compiles to a temp name and rename(2)s over the target so a
# library already dlopened by a live process is never rewritten in place.
set -e
cd "$(dirname "$0")"
OUT="${1:-libetpu.so}"
g++ -O3 -shared -fPIC -pthread -std=c++17 -o "$OUT" \
    etpu_codec.cpp etpu_loader.cpp
echo "built $(pwd)/$OUT"
