#!/bin/sh
# Build the native ETPU codec library in place.
set -e
cd "$(dirname "$0")"
g++ -O3 -shared -fPIC -std=c++17 -o libetpu.so etpu_codec.cpp
echo "built $(pwd)/libetpu.so"
