#!/bin/sh
# Build the native ETPU library (wire codec + batch loader) in place.
set -e
cd "$(dirname "$0")"
g++ -O3 -shared -fPIC -pthread -std=c++17 -o libetpu.so \
    etpu_codec.cpp etpu_loader.cpp
echo "built $(pwd)/libetpu.so"
