// ETPU typed tensor wire codec + framed socket I/O — native implementation.
//
// Same wire format as elephas_tpu/utils/tensor_codec.py (the canonical
// spec):
//   header:  "ETPU" | u8 version | u8 kind | u32 count        (little endian)
//   tensor:  u8 dtype-code | u8 ndim | u64[ndim] dims | raw LE bytes
//
// The Python layer hands raw pointers via ctypes; this library does the
// header packing/parsing and bulk memcpy in one pass, and provides
// single-loop framed send/recv over a connected socket fd so large weight
// payloads move without Python-level chunk bookkeeping.
//
// Buffer-ownership contract (shared with the Python paths — see
// tensor_codec.alloc_frame): every output buffer the caller allocates for
// this library may be UNINITIALIZED. etpu_encode writes every byte of the
// etpu_encoded_size-sized frame (header, dims, tensor bodies are
// contiguous and exhaustive); etpu_recv_frame_body either fills the whole
// length or returns an error, and the Python caller never surfaces the
// buffer on the error path. Nothing here reads a byte it has not written
// or received, so the allocator can skip the zero-fill (bytearray's
// memset cost ~55 ms per 64 MB, GIL-held — the measured +42%/+21% PS
// round-throughput win).
//
// Build: see native/build.sh (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <cstddef>

#include <sys/socket.h>
#include <unistd.h>
#include <errno.h>

extern "C" {

static const char MAGIC[4] = {'E', 'T', 'P', 'U'};
static const uint8_t VERSION = 1;

// dtype code -> element size in bytes; must match tensor_codec._DTYPE_CODES
static const int64_t ITEM_SIZES[] = {
    4,  // 0 float32
    8,  // 1 float64
    4,  // 2 int32
    8,  // 3 int64
    1,  // 4 uint8
    1,  // 5 bool
    2,  // 6 float16
    1,  // 7 int8
    4,  // 8 uint32
    8,  // 9 uint64
    2,  // 10 bfloat16
};
static const int NUM_DTYPES = 11;

// Largest sane per-dimension extent / element count (2^40). Anything above
// is a malformed or hostile payload, not a real tensor.
static const uint64_t MAX_EXTENT = (uint64_t)1 << 40;

static int64_t num_elements(const uint64_t* dims, uint8_t ndim) {
    uint64_t n = 1;
    for (uint8_t i = 0; i < ndim; ++i) {
        uint64_t d = dims[i];
        if (d > MAX_EXTENT) return -1;
        if (d != 0 && n > MAX_EXTENT / (d ? d : 1)) return -1;
        n *= d;
    }
    if (n > MAX_EXTENT) return -1;
    return (int64_t)n;
}

// Total payload size for an array list described by parallel arrays.
// dims_flat holds each tensor's dims consecutively (sum(ndims) entries).
int64_t etpu_encoded_size(int32_t count, const uint8_t* dtype_codes,
                          const uint8_t* ndims, const uint64_t* dims_flat) {
    int64_t size = 10;  // magic + version + kind + count
    const uint64_t* dims = dims_flat;
    for (int32_t i = 0; i < count; ++i) {
        if (dtype_codes[i] >= NUM_DTYPES) return -1;
        int64_t n = num_elements(dims, ndims[i]);
        if (n < 0) return -1;
        size += 2 + 8 * (int64_t)ndims[i];
        size += n * ITEM_SIZES[dtype_codes[i]];
        dims += ndims[i];
    }
    return size;
}

// Encode into out (caller allocates etpu_encoded_size bytes).
// data_ptrs[i] must be C-contiguous little-endian element data.
int32_t etpu_encode(int32_t count, const void* const* data_ptrs,
                    const uint8_t* dtype_codes, const uint8_t* ndims,
                    const uint64_t* dims_flat, uint8_t kind, uint8_t* out) {
    uint8_t* p = out;
    std::memcpy(p, MAGIC, 4); p += 4;
    *p++ = VERSION;
    *p++ = kind;
    uint32_t c = (uint32_t)count;
    std::memcpy(p, &c, 4); p += 4;
    const uint64_t* dims = dims_flat;
    for (int32_t i = 0; i < count; ++i) {
        if (dtype_codes[i] >= NUM_DTYPES) return -1;
        *p++ = dtype_codes[i];
        *p++ = ndims[i];
        std::memcpy(p, dims, 8 * (size_t)ndims[i]);
        p += 8 * (size_t)ndims[i];
        int64_t nbytes = num_elements(dims, ndims[i]) * ITEM_SIZES[dtype_codes[i]];
        std::memcpy(p, data_ptrs[i], (size_t)nbytes);
        p += nbytes;
        dims += ndims[i];
    }
    return 0;
}

// First pass over a payload: validate and report tensor count and total
// dims entries, so the caller can size the description buffers.
// Returns 0 on success, negative error codes on malformed input.
int32_t etpu_decode_probe(const uint8_t* payload, int64_t len,
                          int32_t* out_count, int32_t* out_total_dims,
                          uint8_t* out_kind) {
    if (len < 10 || std::memcmp(payload, MAGIC, 4) != 0) return -1;
    if (payload[4] != VERSION) return -2;
    *out_kind = payload[5];
    uint32_t count;
    std::memcpy(&count, payload + 6, 4);
    int64_t offset = 10;
    int32_t total_dims = 0;
    for (uint32_t i = 0; i < count; ++i) {
        if (offset + 2 > len) return -3;
        uint8_t code = payload[offset];
        uint8_t ndim = payload[offset + 1];
        offset += 2;
        if (code >= NUM_DTYPES) return -4;
        if (offset + 8 * (int64_t)ndim > len) return -5;
        uint64_t dims_buf[255];
        std::memcpy(dims_buf, payload + offset, 8 * (size_t)ndim);
        int64_t n = num_elements(dims_buf, ndim);
        if (n < 0) return -7;  // overflow / hostile dims
        offset += 8 * (int64_t)ndim;
        int64_t nbytes = n * ITEM_SIZES[code];
        if (nbytes > len - offset) return -6;
        offset += nbytes;
        total_dims += ndim;
    }
    *out_count = (int32_t)count;
    *out_total_dims = total_dims;
    return 0;
}

// Second pass: fill per-tensor descriptions. The caller then builds numpy
// arrays directly over payload[data_offsets[i] : ...] (zero copy until the
// final .copy()).
int32_t etpu_decode_describe(const uint8_t* payload, int64_t len,
                             uint8_t* dtype_codes, uint8_t* ndims,
                             uint64_t* dims_flat, int64_t* data_offsets) {
    uint32_t count;
    std::memcpy(&count, payload + 6, 4);
    int64_t offset = 10;
    uint64_t* dims = dims_flat;
    for (uint32_t i = 0; i < count; ++i) {
        uint8_t code = payload[offset];
        uint8_t ndim = payload[offset + 1];
        offset += 2;
        dtype_codes[i] = code;
        ndims[i] = ndim;
        std::memcpy(dims, payload + offset, 8 * (size_t)ndim);
        offset += 8 * (size_t)ndim;
        data_offsets[i] = offset;
        int64_t n = 1;
        for (uint8_t d = 0; d < ndim; ++d) n *= (int64_t)dims[d];
        offset += n * ITEM_SIZES[code];
        dims += ndim;
    }
    (void)len;
    return 0;
}

// ---------------------------------------------------------------- framing
// 8-byte little-endian length prefix + payload, single syscall loops.

int32_t etpu_send_frame(int32_t fd, const uint8_t* payload, int64_t len) {
    uint8_t header[8];
    uint64_t l = (uint64_t)len;
    std::memcpy(header, &l, 8);
    const uint8_t* bufs[2] = {header, payload};
    int64_t lens[2] = {8, len};
    for (int part = 0; part < 2; ++part) {
        const uint8_t* buf = bufs[part];
        int64_t remaining = lens[part];
        while (remaining > 0) {
            ssize_t sent = ::send(fd, buf, (size_t)remaining, MSG_NOSIGNAL);
            if (sent < 0) {
                if (errno == EINTR) continue;
                return -1;
            }
            buf += sent;
            remaining -= sent;
        }
    }
    return 0;
}

// Reads the 8-byte length prefix; returns the payload length (so the
// caller can allocate) or a negative error.
int64_t etpu_recv_frame_len(int32_t fd) {
    uint8_t header[8];
    int64_t remaining = 8;
    uint8_t* p = header;
    while (remaining > 0) {
        ssize_t got = ::recv(fd, p, (size_t)remaining, 0);
        if (got < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (got == 0) return -2;  // peer closed
        p += got;
        remaining -= got;
    }
    uint64_t len;
    std::memcpy(&len, header, 8);
    return (int64_t)len;
}

int32_t etpu_recv_frame_body(int32_t fd, uint8_t* out, int64_t len) {
    int64_t remaining = len;
    uint8_t* p = out;
    while (remaining > 0) {
        ssize_t got = ::recv(fd, p, (size_t)remaining, 0);
        if (got < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (got == 0) return -2;
        p += got;
        remaining -= got;
    }
    return 0;
}

}  // extern "C"
