"""Shared synthetic datasets for the examples (the environment has no
network egress, so MNIST/Boston are replaced by learnable synthetic
problems with the same shapes)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def mnist_like(n_train=6000, n_test=1000, dim=784, classes=10, seed=7):
    centers = np.random.default_rng(123).normal(0.0, 2.0, (classes, dim))
    rng = np.random.default_rng(seed)

    def split(n, s):
        r = np.random.default_rng(s)
        labels = r.integers(0, classes, n)
        x = centers[labels] + r.normal(0.0, 1.0, (n, dim))
        x = (x - x.min()) / (x.max() - x.min())
        return x.astype("float32"), np.eye(classes, dtype="float32")[labels]

    x_train, y_train = split(n_train, seed)
    x_test, y_test = split(n_test, seed + 1)
    return (x_train, y_train), (x_test, y_test)


def housing_like(n_train=404, n_test=102, dim=13, seed=11):
    rng = np.random.default_rng(seed)
    w = rng.normal(0.0, 1.0, dim)
    x_train = rng.normal(0.0, 1.0, (n_train, dim)).astype("float32")
    x_test = rng.normal(0.0, 1.0, (n_test, dim)).astype("float32")
    y_train = (x_train @ w + 20.0 + rng.normal(0, 0.5, n_train)).astype("float32")
    y_test = (x_test @ w + 20.0).astype("float32")
    return (x_train, y_train), (x_test, y_test)


def otto_like(n=2000, dim=93, classes=9, seed=13):
    """Tabular multi-class problem shaped like the Otto product dataset."""
    centers = np.random.default_rng(99).normal(0.0, 1.5, (classes, dim))
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    x = np.abs(centers[labels] + rng.normal(0.0, 1.0, (n, dim)))
    return x.astype("float32"), labels.astype("int64")
