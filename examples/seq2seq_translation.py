"""Seq2seq toy translation: learn to reverse byte sequences.

Encoder-decoder transformer on a synthetic source->target task
(target = reversed source), the classic cross-attention sanity check,
then cached greedy decoding with per-row eos stopping.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elephas_tpu.models.encdec import (EncDecConfig, greedy_decode,
                                       init_params, make_train_step)

config = EncDecConfig(vocab_size=64, num_encoder_layers=2,
                      num_decoder_layers=2, num_heads=4, d_model=64,
                      d_ff=128, max_seq_len=32, dtype=jnp.float32)

rng = np.random.default_rng(0)
n, t = 512, 8
src = rng.integers(3, config.vocab_size, size=(n, t)).astype("int32")
tgt = np.concatenate([src[:, ::-1],
                      np.full((n, 1), config.eos_token_id)],
                     axis=1).astype("int32")

params = init_params(config, jax.random.PRNGKey(0))
tx = optax.adam(3e-3)
opt = tx.init(params)
step = make_train_step(config, tx)
for i in range(200):
    params, opt, loss = step(params, opt, jnp.asarray(src),
                             jnp.asarray(tgt))
    if (i + 1) % 50 == 0:
        print(f"step {i + 1}: loss {float(loss):.4f}")

out = np.asarray(greedy_decode(params, jnp.asarray(src[:64]), t + 1,
                               config))
acc = float((out[:, :t] == src[:64, ::-1]).mean())
print("reversal accuracy:", acc)
