"""Sharded Transformer LM training: dp x tp x sp over a device mesh.

Beyond-the-reference example: trains the flagship transformer with
tensor-parallel parameters, batch-sharded data and ring attention over a
sequence axis — the long-context/distributed-first path. Runs on any
device count (single chip: replicated; 8 devices: 2x2x2 mesh).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elephas_tpu.models.transformer import (TransformerConfig, init_params,
                                            make_train_step, shard_params)

config = TransformerConfig(vocab_size=512, num_layers=4, num_heads=8,
                           d_model=256, d_ff=512, max_seq_len=256)

n = len(jax.devices())
if n >= 8:
    dp, tp, sp = 2, 2, 2
elif n >= 4:
    dp, tp, sp = 2, 2, 1
elif n >= 2:
    dp, tp, sp = 2, 1, 1
else:
    dp, tp, sp = 1, 1, 1
mesh = Mesh(np.array(jax.devices()[:dp * tp * sp]).reshape(dp, tp, sp),
            ("data", "model", "seq"))
print(f"mesh: data={dp} model={tp} seq={sp}")

params = shard_params(init_params(config, jax.random.PRNGKey(0)), config, mesh)
tx = optax.adam(3e-4)
opt_state = jax.jit(tx.init)(params)

# synthetic token stream with local structure so the LM has something to learn
rng = np.random.default_rng(0)
base = rng.integers(0, config.vocab_size, 128)
tokens = np.stack([np.roll(base, i) for i in range(8 * dp)]).astype(np.int32)
tokens = jax.device_put(tokens[:, :128], NamedSharding(mesh, P("data", "seq")))

step = make_train_step(config, tx, mesh=mesh,
                       seq_axis="seq" if sp > 1 else None)
for i in range(20):
    params, opt_state, loss = step(params, opt_state, tokens)
    if i % 5 == 0:
        print(f"step {i}: loss {float(loss):.4f}")
print(f"final loss: {float(loss):.4f}")
