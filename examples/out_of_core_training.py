"""Out-of-core training over file-backed data.

The reference's training data lives distributed in RDD partitions that
each executor materializes on demand (``elephas/worker.py:36-38``).
Here the data lives on disk — memory-mapped ``.npy`` (or Parquet via
``Dataset.from_parquet``) — and fit/predict/evaluate stream it: peak
host memory is O(batch), never O(dataset), and predictions can stream
straight back to a ``.npy`` memmap without accumulating in memory.
"""
import os
import tempfile

import numpy as np
from common import mnist_like

from elephas_tpu.data import Dataset
from elephas_tpu.models import SGD, Activation, Dense, Dropout, Sequential
from elephas_tpu.tpu_model import TPUModel

batch_size = 64
epochs = 3

# Stage the dataset as sharded .npy files — the multi-part shape real
# data arrives in (Spark writes directories of part files). Each column
# is an ordered list of shards, concatenated lazily; any size, never
# loaded whole. (A directory of parquet part files works the same way:
# ``Dataset.from_parquet_dir(dirpath, ["features"])``.)
(x_train, y_train), (x_test, y_test) = mnist_like()
workdir = tempfile.mkdtemp(prefix="elephas_ooc_")
half = len(x_train) // 2
x_shards, y_shards = [], []
for i, sl in enumerate((slice(0, half), slice(half, None))):
    xp = os.path.join(workdir, f"x-{i:05d}.npy")
    yp = os.path.join(workdir, f"y-{i:05d}.npy")
    np.save(xp, x_train[sl])
    np.save(yp, y_train[sl])
    x_shards.append(xp)
    y_shards.append(yp)

dataset = Dataset.from_npy(x_shards, y_shards, num_partitions=4)

model = Sequential([Dense(128, input_dim=784), Activation("relu"),
                    Dropout(0.2),
                    Dense(128), Activation("relu"), Dropout(0.2),
                    Dense(10), Activation("softmax")])
model.compile(SGD(learning_rate=0.05), "categorical_crossentropy", ["acc"])

tpu_model = TPUModel(model, mode="synchronous", sync_mode="step",
                     batch_size=batch_size)
tpu_model.fit(dataset, epochs=epochs, batch_size=batch_size, verbose=1,
              validation_split=0.1)

src = dataset.columns[0]
print(f"rows read during fit: {src.rows_read} "
      f"(max single read {src.max_read_rows} rows — one batch)")

# streamed inference: predictions land in a .npy memmap, in input order
pred_path = os.path.join(workdir, "predictions.npy")
tpu_model.predict(dataset, out=pred_path)
preds = np.load(pred_path, mmap_mode="r")
acc = float(np.mean(np.argmax(np.asarray(preds[: len(y_train)]), axis=1)
                    == np.argmax(y_train, axis=1)))
print(f"train accuracy from streamed predictions: {acc:.4f}")

score = tpu_model.evaluate(x_test, y_test)
print(f"test loss/acc: {score}")
