"""Serving fast path: speculative decoding + continuous batching.

Decode is weight-bandwidth-bound — every token re-reads the model from
HBM. This example shows the two serving-side answers working together:

1. **Speculative decoding**: a draft model proposes ``gamma`` tokens,
   the full model verifies them in ONE cached block forward
   (``decode_block``), emitting ``1 + gamma*acceptance`` tokens per
   weight read. Two ends of the acceptance spectrum are shown: a
   perfect draft (the target itself — every proposal accepted, rounds
   collapse by gamma+1x) and an unrelated random draft (acceptance ~0
   — output STILL exact, because greedy verification never trusts the
   draft). A real deployment's distilled/truncated draft sits between.
2. **Continuous batching**: ``DecodeEngine`` runs a fixed slot batch
   where each request sits at its OWN sequence position; new requests
   join the moment a slot frees. Per-request output equals the solo
   ``generate`` decode.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from elephas_tpu import DecodeEngine
from elephas_tpu.models.transformer import (TransformerConfig, generate,
                                            init_params)
from elephas_tpu.models.speculative import speculative_generate

target_cfg = TransformerConfig(vocab_size=256, num_layers=4, num_heads=4,
                               d_model=64, d_ff=128, max_seq_len=96,
                               dtype=jnp.float32)
draft_cfg = TransformerConfig(vocab_size=256, num_layers=1, num_heads=4,
                              d_model=64, d_ff=128, max_seq_len=96,
                              dtype=jnp.float32)
params = init_params(target_cfg, jax.random.PRNGKey(0))
draft_params = init_params(draft_cfg, jax.random.PRNGKey(7))

rng = np.random.default_rng(0)
prompt = rng.integers(0, 256, (4, 8))

ref = np.asarray(generate(params, prompt, 24, target_cfg))
# perfect draft (the target itself): acceptance 1.0, gamma+1 tokens/round
spec, stats = speculative_generate(params, params, prompt, 24,
                                   target_cfg, target_cfg, gamma=4,
                                   return_stats=True)
assert (ref == np.asarray(spec)).all(), "greedy spec-decode must be exact"
print(f"perfect draft:  exact greedy match; {stats['rounds']} rounds for "
      f"24 tokens (sequential decode would take 24), "
      f"acceptance {stats['draft_acceptance']:.2f}")
# unrelated random draft: near-zero acceptance, output still exact
spec, stats = speculative_generate(params, draft_params, prompt, 24,
                                   target_cfg, draft_cfg, gamma=4,
                                   return_stats=True)
assert (ref == np.asarray(spec)).all(), "exactness must not need the draft"
print(f"random draft:   exact greedy match; {stats['rounds']} rounds, "
      f"acceptance {stats['draft_acceptance']:.2f} — correctness never "
      f"depends on draft quality")

# ---- the middle of the spectrum: distill a real draft against the
# target (train the target on a predictable corpus first, so there is
# structure for the draft to learn)
import optax

from elephas_tpu.models.distill import make_distill_step
from elephas_tpu.models.transformer import make_train_step

rows = jnp.asarray(rng.integers(0, 4, (8, 33)) + 97)  # tiny 4-letter LM
tx = optax.adam(1e-2)
opt = tx.init(params)
train = make_train_step(target_cfg, tx)
for _ in range(40):
    params, opt, _ = train(params, opt, rows)

dtx = optax.adam(3e-3)
dopt = dtx.init(draft_params)
distill = make_distill_step(draft_cfg, target_cfg, dtx, temperature=2.0,
                            hard_weight=0.1)
for _ in range(120):
    draft_params, dopt, dloss = distill(draft_params, params, dopt, rows)

prompt2 = np.asarray(rows[:4, :8])
ref2 = np.asarray(generate(params, prompt2, 24, target_cfg))
spec, stats = speculative_generate(params, draft_params, prompt2, 24,
                                   target_cfg, draft_cfg, gamma=4,
                                   return_stats=True)
assert (ref2 == np.asarray(spec)).all()
print(f"distilled draft: exact greedy match; {stats['rounds']} rounds, "
      f"acceptance {stats['draft_acceptance']:.2f} — the practical "
      f"middle ground a distilled draft buys")

# ---- continuous batching: 6 requests through 2 slots
prompts = [rng.integers(0, 256, int(n)) for n in rng.integers(4, 12, 6)]
eng = DecodeEngine(params, target_cfg, max_slots=2)
outs = eng.run(prompts, max_new_tokens=12)
for i, (p, o) in enumerate(zip(prompts, outs)):
    solo = list(np.asarray(generate(params, p[None], 12, target_cfg))[0])
    assert o == solo, f"request {i} diverged from its solo decode"
print(f"continuous batching: {len(prompts)} requests x 12 tokens through "
      f"2 slots, every output identical to its solo decode")

# ---- the composition: speculative continuous batching — the distilled
# draft rides inside the engine, so every slot advances by 1+accepted
# tokens per host round trip
spec_eng = DecodeEngine(params, target_cfg, max_slots=2,
                        draft_params=draft_params, draft_config=draft_cfg,
                        gamma=4)
rids = [spec_eng.submit(p, 12) for p in prompts]
steps = 0
while spec_eng.pending:
    spec_eng.step()
    steps += 1
for i, (p, r) in enumerate(zip(prompts, rids)):
    solo = list(np.asarray(generate(params, p[None], 12, target_cfg))[0])
    assert spec_eng.result(r) == solo, f"request {i} diverged"
print(f"speculative continuous batching: same 6 requests drained in "
      f"{steps} host steps (plain mode needs ~{3 * 12 + 1}), outputs "
      f"still identical to solo decodes")

# ---- prefix caching: a shared system prompt is prefilled once, ever —
# each request admission reuses its KV and runs one decode_block over
# just the suffix (vLLM-style prefix sharing, explicit registration)
system = list(rng.integers(0, 256, 10))
chats = [np.asarray(system + list(rng.integers(0, 256, int(n))))
         for n in (3, 5, 3, 7)]
pc_eng = DecodeEngine(params, target_cfg, max_slots=2)
pc_eng.register_prefix(system)
outs = pc_eng.run(chats, max_new_tokens=12)
for i, (p, o) in enumerate(zip(chats, outs)):
    solo = list(np.asarray(generate(params, p[None], 12, target_cfg))[0])
    assert o == solo, f"request {i} diverged under prefix caching"
stats = pc_eng.stats
print(f"prefix caching: {stats['prefix_hits']} admissions reused the "
      f"{len(system)}-token system prompt ({stats['prefix_tokens_reused']} "
      f"prefill tokens skipped), outputs identical to solo decodes")
