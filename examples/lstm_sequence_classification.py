"""Distributed LSTM sequence classification through TPUModel.

The reference era's Keras LSTM workload on the TPU framework: embedding
-> LSTM -> softmax, trained data-parallel with the sync-step trainer
(whole epoch in one jitted program), then distributed predict parity.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu.models import LSTM, Adam, Dense, Embedding, Sequential
from elephas_tpu.tpu_model import TPUModel
from elephas_tpu.utils.dataset_utils import to_dataset

# task: is the count of token 1 in the window even?
rng = np.random.default_rng(0)
n, t, vocab = 4096, 16, 32
x = rng.integers(0, vocab, size=(n, t)).astype("int32")
y_bit = ((x == 1).sum(axis=1) % 2 == 0).astype("float32")
y = np.stack([1 - y_bit, y_bit], axis=1)

model = Sequential([Embedding(vocab, 16, input_shape=(t,)),
                    LSTM(32),
                    Dense(2, activation="softmax")])
model.compile(Adam(learning_rate=5e-3), "categorical_crossentropy",
              metrics=["acc"], seed=0)

tpu_model = TPUModel(model, mode="synchronous", sync_mode="step",
                     num_workers=4)
tpu_model.fit(to_dataset(x, y), epochs=6, batch_size=128, verbose=1,
              validation_split=0.1)

preds = tpu_model.predict(x[:1024])
acc = float((np.asarray(preds).argmax(1) == y[:1024].argmax(1)).mean())
print("accuracy:", acc)
