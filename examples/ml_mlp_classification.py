"""ML-pipeline classification: Estimator -> Transformer over a DataFrame.

Port of ``examples/ml_mlp_classification.py`` from the reference.
"""
import numpy as np
from common import mnist_like

from elephas_tpu.ml import Estimator, to_data_frame
from elephas_tpu.models import (SGD, Activation, Dense, Dropout, Sequential,
                                serialize_optimizer)

(x_train, y_train), (x_test, y_test) = mnist_like(n_train=2000, n_test=400)

model = Sequential()
model.add(Dense(128, input_dim=784))
model.add(Activation("relu"))
model.add(Dropout(0.2))
model.add(Dense(128))
model.add(Activation("relu"))
model.add(Dropout(0.2))
model.add(Dense(10))
model.add(Activation("softmax"))
model.build()

train_df = to_data_frame(x_train, y_train, categorical=True)
test_df = to_data_frame(x_test, y_test, categorical=True)

estimator = Estimator(
    model_config=model.to_json(),
    optimizer_config=serialize_optimizer(SGD(learning_rate=0.1)),
    loss="categorical_crossentropy",
    metrics=["acc"],
    mode="synchronous",
    categorical=True,
    nb_classes=10,
    epochs=5,
    batch_size=64,
    validation_split=0.1,
    num_workers=4,
    verbose=0,
)

fitted = estimator.fit(train_df)
result = fitted.transform(test_df)

accuracy = np.mean([int(np.argmax(p)) == int(label) for p, label
                    in zip(result["prediction"], result["label"])])
print("Pipeline test accuracy:", accuracy)
