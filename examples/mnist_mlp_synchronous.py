"""MNIST-style MLP, synchronous distributed training.

Port of the reference's canonical example
(``examples/mnist_mlp_spark_synchronous.py``): 784-128-128-10 MLP with
dropout, SGD lr=0.1, batch 64, mode='synchronous'.
"""
from common import mnist_like

from elephas_tpu.models import SGD, Activation, Dense, Dropout, Sequential
from elephas_tpu.tpu_model import TPUModel
from elephas_tpu.utils import to_dataset

batch_size = 64
epochs = 3

(x_train, y_train), (x_test, y_test) = mnist_like()

model = Sequential()
model.add(Dense(128, input_dim=784))
model.add(Activation("relu"))
model.add(Dropout(0.2))
model.add(Dense(128))
model.add(Activation("relu"))
model.add(Dropout(0.2))
model.add(Dense(10))
model.add(Activation("softmax"))
model.compile(SGD(learning_rate=0.1), "categorical_crossentropy", ["acc"])

dataset = to_dataset(x_train, y_train)

tpu_model = TPUModel(model, frequency="epoch", mode="synchronous")
tpu_model.fit(dataset, epochs=epochs, batch_size=batch_size, verbose=1,
              validation_split=0.1)

score = tpu_model.evaluate(x_test, y_test)
print("Test loss:", score[0])
print("Test accuracy:", score[1])
