"""End-to-end tabular pipeline: label indexing + feature scaling + training.

Port of ``examples/ml_pipeline_otto.py`` from the reference (Spark
StringIndexer + StandardScaler + ElephasEstimator pipeline), with the
preprocessing stages done in numpy/pandas.
"""
import numpy as np
from common import otto_like

from elephas_tpu.ml import Estimator, to_data_frame
from elephas_tpu.models import (Adam, Activation, Dense, Dropout, Sequential,
                                serialize_optimizer)

x, labels = otto_like()

# "StringIndexer": map raw labels to contiguous indices
classes, indexed = np.unique(labels, return_inverse=True)
nb_classes = len(classes)

# "StandardScaler": zero-mean unit-variance features
mean, std = x.mean(axis=0), x.std(axis=0) + 1e-8
x = (x - mean) / std

split = int(0.8 * len(x))
train_df = to_data_frame(x[:split], indexed[:split].astype(float),
                         categorical=False)
test_df = to_data_frame(x[split:], indexed[split:].astype(float),
                        categorical=False)

model = Sequential()
model.add(Dense(256, input_dim=x.shape[1]))
model.add(Activation("relu"))
model.add(Dropout(0.3))
model.add(Dense(256))
model.add(Activation("relu"))
model.add(Dropout(0.3))
model.add(Dense(nb_classes))
model.add(Activation("softmax"))
model.build()

estimator = Estimator(
    model_config=model.to_json(),
    optimizer_config=serialize_optimizer(Adam(learning_rate=0.001)),
    loss="categorical_crossentropy",
    metrics=["acc"],
    mode="synchronous",
    categorical=True,
    nb_classes=nb_classes,
    epochs=8,
    batch_size=128,
    validation_split=0.1,
    num_workers=4,
    verbose=0,
)

fitted = estimator.fit(train_df)
result = fitted.transform(test_df)

accuracy = np.mean([int(np.argmax(p)) == int(label) for p, label
                    in zip(result["prediction"], result["label"])])
print("Otto-style pipeline accuracy:", accuracy)
