"""LabeledPoint training with TPUMatrixModel.

Port of ``examples/mllib_mlp.py`` from the reference: train on a Dataset of
LabeledPoints and predict on dense linalg types.
"""
from common import mnist_like

from elephas_tpu.mllib import to_matrix
from elephas_tpu.models import SGD, Dense, Sequential
from elephas_tpu.tpu_model import TPUMatrixModel
from elephas_tpu.utils import to_labeled_points

batch_size = 64
epochs = 3

(x_train, y_train), (x_test, y_test) = mnist_like()

model = Sequential()
model.add(Dense(128, input_dim=784, activation="relu"))
model.add(Dense(128, activation="relu"))
model.add(Dense(10, activation="softmax"))
model.compile(SGD(learning_rate=0.1), "categorical_crossentropy", ["acc"])

lp_dataset = to_labeled_points(x_train, y_train, categorical=True)

tpu_model = TPUMatrixModel(model, frequency="epoch", mode="synchronous",
                           num_workers=4)
tpu_model.fit(lp_dataset, epochs=epochs, batch_size=batch_size, verbose=0,
              validation_split=0.1, categorical=True, nb_classes=10)

preds = tpu_model.predict(to_matrix(x_test[:8]))
print("Predictions:", preds.toArray().argmax(axis=1))
