"""Vision Transformer image classification, dp x tp sharded.

Trains a small ViT on a synthetic patch-localization task (no network
egress in this environment): class k means a bright patch at cell k.
Runs on any device count — single chip replicated, multi-device dp x tp.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elephas_tpu.models.vit import (ViTConfig, forward, init_params,
                                    make_train_step, shard_params)

config = ViTConfig(image_size=32, patch_size=8, channels=3, num_classes=16,
                   num_layers=4, num_heads=4, d_model=128, d_ff=256,
                   dtype=jnp.float32)

rng = np.random.default_rng(0)
n = 2048
labels = rng.integers(0, config.num_classes, n)
x = rng.normal(0.0, 0.3, (n, 32, 32, 3))
for i, k in enumerate(labels):
    r, c = divmod(int(k), 4)
    x[i, r * 8:(r + 1) * 8, c * 8:(c + 1) * 8, :] += 1.5
x = x.astype("float32")
labels = labels.astype("int32")

ndev = len(jax.devices())
dp = 4 if ndev >= 8 else (2 if ndev >= 2 else 1)
tp = 2 if ndev >= 2 * dp else 1
mesh = (Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
             ("data", "model")) if dp * tp > 1 else None)
print(f"mesh: data={dp} model={tp}")

params = init_params(config, jax.random.PRNGKey(0))
if mesh is not None:
    params = shard_params(params, config, mesh)
tx = optax.adam(1e-3)
opt_state = jax.jit(tx.init)(params)
step = make_train_step(config, tx, mesh=mesh)

batch = 256
for epoch in range(5):
    order = rng.permutation(n)
    losses = []
    for i in range(n // batch):
        xb = jnp.asarray(x[order[i * batch:(i + 1) * batch]])
        yb = jnp.asarray(labels[order[i * batch:(i + 1) * batch]])
        if mesh is not None:
            xb = jax.device_put(xb, NamedSharding(
                mesh, P("data", None, None, None)))
            yb = jax.device_put(yb, NamedSharding(mesh, P("data")))
        params, opt_state, loss = step(params, opt_state, xb, yb)
        losses.append(float(loss))
    print(f"epoch {epoch + 1}: loss {np.mean(losses):.4f}")

preds = np.asarray(forward(params, jnp.asarray(x[:512]), config)).argmax(1)
print("train accuracy:", float((preds == labels[:512]).mean()))
