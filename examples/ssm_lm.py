"""Selective SSM (Mamba-style) LM: train with one parallel scan per
layer, decode with O(1) state.

The attention transformer's KV cache grows with context; the SSM's
decode state is a constant ``(batch, d_inner)`` per layer — this
example trains a small selective SSM on byte text and then streams a
continuation whose serving memory would be identical at 1k or 1M
context. The reference has no sequence models at all (SURVEY.md §2 —
user-supplied Keras MLPs/convs); this family is beyond-parity breadth.

Run: JAX_PLATFORMS=cpu python examples/ssm_lm.py
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elephas_tpu.models.ssm import (SSMConfig, init_ssm_params,
                                    init_ssm_state, make_ssm_train_step,
                                    ssm_generate)
from elephas_tpu.utils.text import ByteTokenizer

tok = ByteTokenizer()
TEXT = ("the quick brown fox jumps over the lazy dog. " * 40)

config = SSMConfig(vocab_size=tok.vocab_size, num_layers=2, d_model=64,
                   d_inner=128)
params = init_ssm_params(config, jax.random.PRNGKey(0))

# pack the corpus into fixed windows
ids = np.asarray(tok.encode(TEXT), np.int32)
seq = 48
n = (len(ids) - 1) // seq
tokens = jnp.asarray(ids[: n * seq].reshape(n, seq))

tx = optax.adam(3e-3)
step = make_ssm_train_step(config, tx)
opt_state = tx.init(params)
first = last = None
for epoch in range(120):
    params, opt_state, loss = step(params, opt_state, tokens)
    first = float(loss) if first is None else first
    last = float(loss)
print(f"loss {first:.3f} -> {last:.3f} over 120 steps "
      f"(one associative scan per layer per step)")
assert last < 0.25 * first

prompt = np.asarray(tok.encode("the quick brown "))[None]
out = np.asarray(ssm_generate(params, jnp.asarray(prompt), 24, config))
print("continuation:", repr(tok.decode(out[0])))

state = init_ssm_state(config, 1)
state_bytes = sum(int(np.prod(s.shape)) * s.dtype.itemsize
                  for s in state.values())
print(f"decode state: {state_bytes} bytes TOTAL, constant in context "
      f"length (a transformer KV cache grows per token)")
assert "fox" in tok.decode(out[0]) or "quick" in tok.decode(out[0])
