"""FSDP (ZeRO-3) transformer LM training via the TransformerModel API.

Every large parameter, gradient, and Adam moment lives 1/dp-sharded over
the data axis; GSPMD inserts the all-gathers and reduce-scatters. With
GQA (2 kv-head groups) and the chunked-vocab loss, this is the
memory-lean large-model configuration.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from elephas_tpu.models import Adam, TransformerModel
from elephas_tpu.models.transformer import TransformerConfig
from elephas_tpu.tpu_model import TPUModel

config = TransformerConfig(vocab_size=512, num_layers=4, num_heads=8,
                           num_kv_heads=2, d_model=256, d_ff=512,
                           max_seq_len=128, positional="rope",
                           loss_vocab_chunk=128)

model = TransformerModel(config, tensor_parallel=1, fsdp=True)
model.compile(Adam(learning_rate=1e-3), seed=0)

rng = np.random.default_rng(0)
tokens = rng.integers(0, config.vocab_size, size=(2048, 64)).astype("int32")

tpu_model = TPUModel(model, mode="synchronous")
tpu_model.fit(tokens, epochs=3, batch_size=64, verbose=1,
              validation_split=0.0)

emb = model.params["embed"]["tokens"]
print("devices:", len(jax.devices()),
      "| embedding shard:", emb.addressable_shards[0].data.shape,
      "of", emb.shape)
print("loss history:", [round(v, 4)
                        for v in tpu_model.training_histories[-1]["loss"]])
