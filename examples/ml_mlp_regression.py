"""ML-pipeline regression (port of ``examples/ml_mlp_regression.py``)."""
import numpy as np
from common import housing_like

from elephas_tpu.ml import Estimator, to_data_frame
from elephas_tpu.models import Adam, Dense, Sequential, serialize_optimizer

(x_train, y_train), (x_test, y_test) = housing_like()

model = Sequential()
model.add(Dense(64, activation="relu", input_shape=(13,)))
model.add(Dense(64, activation="relu"))
model.add(Dense(1, activation="linear"))
model.build()

train_df = to_data_frame(x_train, y_train, categorical=False)
test_df = to_data_frame(x_test, y_test, categorical=False)

estimator = Estimator(
    model_config=model.to_json(),
    optimizer_config=serialize_optimizer(Adam(learning_rate=0.01)),
    loss="mse",
    metrics=["mae"],
    mode="synchronous",
    categorical=False,
    nb_classes=1,
    epochs=30,
    batch_size=64,
    validation_split=0.1,
    num_workers=2,
    verbose=0,
)

fitted = estimator.fit(train_df)
result = fitted.transform(test_df)

mae = np.mean([abs(pred - label) for pred, label
               in zip(result["prediction"], result["label"])])
print("Pipeline test MAE:", mae)
