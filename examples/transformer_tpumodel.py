"""Flagship transformer LM through the distributed TPUModel API.

The unified path: the same ``TPUModel.fit`` that drives the Keras-style
models drives the mesh-sharded transformer — callbacks fire per epoch,
``ModelCheckpoint`` writes resumable state (params + optimizer moments),
and ``EarlyStopping`` can stop sharded training mid-run. Train, stop,
restore bit-exact, continue.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from elephas_tpu.models import Adam, EarlyStopping, ModelCheckpoint, TransformerModel
from elephas_tpu.models.transformer import TransformerConfig
from elephas_tpu.tpu_model import TPUModel

config = TransformerConfig(vocab_size=512, num_layers=4, num_heads=8,
                           d_model=256, d_ff=512, max_seq_len=128)

# tensor_parallel splits attention heads / MLP hidden over the mesh's
# model axis; the rest of the devices form the data axis
tp = 2 if len(jax.devices()) % 2 == 0 and len(jax.devices()) > 1 else 1
model = TransformerModel(config, tensor_parallel=tp)
model.compile(Adam(learning_rate=3e-4), seed=0)

# synthetic corpus: random token rows (swap in real tokenized text)
tokens = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (256, 128), 0,
                                       config.vocab_size))

ckpt_dir = os.path.join(tempfile.gettempdir(), "elephas_tpu_transformer_ckpt")
tpu_model = TPUModel(model, mode="synchronous")
tpu_model.fit(tokens, epochs=5, batch_size=16, verbose=1,
              validation_split=0.1,
              callbacks=[ModelCheckpoint(ckpt_dir),
                         EarlyStopping(monitor="val_loss", patience=2)])

history = tpu_model.training_histories[-1]
print("loss curve:", [round(v, 4) for v in history["loss"]])

# resume bit-exact in a fresh process/instance
resumed = TransformerModel(config, tensor_parallel=tp)
resumed.compile(Adam(learning_rate=3e-4))
step = resumed.restore_training_state(ckpt_dir)
print(f"restored epoch {step}; continuing training")
TPUModel(resumed, mode="synchronous").fit(
    tokens, epochs=1, batch_size=16, verbose=1, validation_split=0.1)

print("eval loss:", tpu_model.evaluate(tokens[:32], None))
