"""Long-context LM: sliding-window attention x sequence parallelism.

The Mistral-style configuration the reference could never express: the
sequence axis is sharded over a device mesh (ring attention streams k/v
shards over ICI), the attention window bounds each position's context,
and the ring statically SKIPS hops whose shard is entirely outside the
band — a narrow window on a long ring pays O(window) compute and
communication, not O(seq). On TPU each hop's local block runs the Pallas
flash kernel (``ring_flash_attention``); elsewhere the einsum ring.

Run on the 8-device virtual mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_windowed_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from elephas_tpu.models import Adam, TransformerModel
from elephas_tpu.models.transformer import TransformerConfig
from elephas_tpu.ops.ring_attention import ring_num_hops
from elephas_tpu.tpu_model import TPUModel

SEQ = 256
SEQ_MESH = 4
WINDOW = 48

config = TransformerConfig(vocab_size=512, num_layers=4, num_heads=8,
                           num_kv_heads=2, d_model=256, d_ff=512,
                           max_seq_len=SEQ, positional="rope",
                           attention_window=WINDOW)

model = TransformerModel(config, sequence_parallel=SEQ_MESH)
model.compile(Adam(learning_rate=1e-3), seed=0)

shard = SEQ // SEQ_MESH
print(f"seq {SEQ} over {SEQ_MESH}-way seq mesh (shard {shard}), "
      f"window {WINDOW}: ring visits "
      f"{ring_num_hops(SEQ_MESH, shard, WINDOW)}/{SEQ_MESH} hops "
      "(out-of-band hops skipped statically)")

# synthetic corpus with local structure a windowed model can learn:
# next token = (previous token + 1) mod vocab, seeded randomly per row
rng = np.random.default_rng(0)
starts = rng.integers(0, config.vocab_size, size=(512, 1))
tokens = ((starts + np.arange(SEQ)) % config.vocab_size).astype("int32")

tpu_model = TPUModel(model, mode="synchronous")
tpu_model.fit(tokens, epochs=3, batch_size=32, verbose=1,
              validation_split=0.0)

history = tpu_model.training_histories[-1]
print("loss history:", [round(v, 4) for v in history["loss"]])
assert history["loss"][-1] < history["loss"][0]
