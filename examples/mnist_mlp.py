"""MNIST-style MLP with default (hogwild) training mode.

Port of ``examples/mnist_mlp_spark.py`` from the reference (which trains
with the default asynchronous/hogwild configuration).
"""
from common import mnist_like

from elephas_tpu.models import SGD, Activation, Dense, Dropout, Sequential
from elephas_tpu.tpu_model import TPUModel
from elephas_tpu.utils import to_dataset

batch_size = 64
epochs = 3

(x_train, y_train), (x_test, y_test) = mnist_like()

model = Sequential()
model.add(Dense(128, input_dim=784, activation="relu"))
model.add(Dropout(0.2))
model.add(Dense(128, activation="relu"))
model.add(Dropout(0.2))
model.add(Dense(10, activation="softmax"))
model.compile(SGD(learning_rate=0.1), "categorical_crossentropy", ["acc"])

dataset = to_dataset(x_train, y_train)

tpu_model = TPUModel(model, frequency="batch", mode="hogwild", port=4002)
tpu_model.fit(dataset, epochs=epochs, batch_size=batch_size, verbose=1,
              validation_split=0.1)

score = tpu_model.evaluate(x_test, y_test)
print("Test loss:", score[0])
print("Test accuracy:", score[1])
