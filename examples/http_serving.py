"""Online HTTP serving: ServingServer over a continuous-batching engine.

The reference's only inference path is offline Spark ``mapPartitions``
prediction (``elephas/spark_model.py:235-272``); this example runs the
TPU framework's online half end to end — an HTTP server whose device
batch interleaves concurrent client requests, with per-request sampling
settings and cancellation on the wire.

Run: JAX_PLATFORMS=cpu python examples/http_serving.py
"""
import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np

from elephas_tpu import DecodeEngine, ServingServer
from elephas_tpu.models.transformer import (TransformerConfig, generate,
                                            init_params)
from elephas_tpu.utils.text import ByteTokenizer

tok = ByteTokenizer()
config = TransformerConfig(vocab_size=tok.vocab_size, num_layers=2,
                           num_heads=4, d_model=64, d_ff=128,
                           max_seq_len=96, dtype=jnp.float32)
params = init_params(config, jax.random.PRNGKey(0))


def post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=120).read())


# steps_per_sync trades admission granularity for fewer host round
# trips — the right setting when dispatch latency dominates (see the
# serving guide); prefix caching pins the shared "system prompt"
# ...and the paged block pool holds HALF the contiguous cache's
# positions (4 slots x 96 = 384 vs 23 allocatable blocks x 8 = 184):
# admission queues when the pool runs dry, blocks recycle on retirement
engine = DecodeEngine(params, config, max_slots=4, steps_per_sync=2,
                      paged=(24, 8))
system = tok.encode("SYSTEM: ")
engine.register_prefix(system)

with ServingServer(engine, tokenizer=tok) as srv:
    print(f"serving on 127.0.0.1:{srv.port}")

    prompts = ["SYSTEM: hello", "SYSTEM: goodbye", "SYSTEM: what",
               "plain prompt", "SYSTEM: again"]
    results = {}

    def client(i):
        results[i] = post(srv.port, "/v1/generate",
                          {"text": prompts[i], "max_new_tokens": 16})

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i, text in enumerate(prompts):
        ref = list(np.asarray(generate(
            params, jnp.asarray(tok.encode(text))[None], 16, config))[0])
        assert results[i]["tokens"] == ref, f"client {i} diverged"
    stats = post(srv.port, "/v1/submit",
                 {"text": "one more", "max_new_tokens": 4}) and \
        json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/stats", timeout=120).read())
    print(f"{len(prompts)} concurrent clients ≡ solo decode; "
          f"prefix hits {stats.get('prefix_hits', 0)}, "
          f"tokens/step {stats['tokens_per_step']:.2f}")
print("server stopped cleanly")
