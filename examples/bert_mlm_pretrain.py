"""BERT masked-language-model pretraining + classification fine-tune.

Pretrains a small bidirectional encoder with dynamic 80/10/10 masking on
byte-tokenized synthetic text, then fine-tunes a classifier head (linear
probe) — the full BERT recipe end to end on the framework's own
tokenizer and encoder.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elephas_tpu.models.bert import (BertConfig, init_classifier_head,
                                     init_params, make_classifier_train_step,
                                     make_mlm_train_step)
from elephas_tpu.utils.text import ByteTokenizer

tok = ByteTokenizer()
config = BertConfig(vocab_size=tok.vocab_size, num_layers=4, num_heads=4,
                    d_model=128, d_ff=256, max_seq_len=64,
                    mask_token_id=tok.bos_id,  # reuse a spare special id
                    pad_token_id=tok.pad_id, max_predictions=12,
                    dtype=jnp.float32)

sentences = ["the quick brown fox jumps over the lazy dog",
             "pack my box with five dozen liquor jugs",
             "how vexingly quick daft zebras jump"]
rows = tok.encode_batch([s for s in sentences for _ in range(64)],
                        seq_len=48)

params = init_params(config, jax.random.PRNGKey(0))
tx = optax.adam(3e-4)
opt = tx.init(params)
step = make_mlm_train_step(config, tx)

tokens = jnp.asarray(rows)
for i in range(30):
    params, opt, loss = step(params, opt, tokens, jax.random.PRNGKey(i))
    if (i + 1) % 10 == 0:
        print(f"mlm step {i + 1}: loss {float(loss):.4f}")

# fine-tune: classify which pangram a (unmasked) row is
labels = jnp.asarray(np.arange(len(rows)) // 64, dtype=jnp.int32)
head = init_classifier_head(config, len(sentences), jax.random.PRNGKey(1))
state = {"params": params, "head": head}
ft_tx = optax.adam(1e-3)
ft_opt = ft_tx.init({"head": head})
ft_step = make_classifier_train_step(config, ft_tx, freeze_encoder=True)
for i in range(20):
    state, ft_opt, ft_loss = ft_step(state, ft_opt, tokens, labels)
print(f"fine-tune loss: {float(ft_loss):.4f}")

from elephas_tpu.models.bert import classify

preds = np.asarray(classify(state["params"], state["head"], tokens,
                            config)).argmax(1)
print("probe accuracy:", float((preds == np.asarray(labels)).mean()))
