"""Packed-sequence LM pretraining with the modern-config transformer.

The realistic pretraining data path: greedy document packing (segment
ids, no cross-document attention), RoPE + GQA + SwiGLU + RMSNorm
architecture, chunked-vocab loss, and residual dropout — all through the
standard ``make_train_step(packed=True)``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elephas_tpu.models.transformer import (TransformerConfig, init_params,
                                            make_train_step)
from elephas_tpu.utils.text import ByteTokenizer

tok = ByteTokenizer()
docs = ["the quick brown fox jumps over the lazy dog. ",
        "pack my box with five dozen liquor jugs! ",
        "sphinx of black quartz, judge my vow. "] * 40
rows, segs = tok.pack_documents(docs, seq_len=64)
print(f"packed {len(docs)} docs into {rows.shape[0]} rows of 64 "
      f"({100 * (segs > 0).mean():.0f}% non-pad)")

config = TransformerConfig(vocab_size=tok.vocab_size, num_layers=2,
                           num_heads=4, num_kv_heads=2, d_model=64,
                           d_ff=128, max_seq_len=64, positional="rope",
                           mlp_variant="swiglu", norm="rmsnorm",
                           loss_vocab_chunk=128, dropout_rate=0.1,
                           dtype=jnp.float32)
params = init_params(config, jax.random.PRNGKey(0))
tx = optax.adamw(3e-3)
opt = tx.init(params)
step = make_train_step(config, tx, packed=True)

tokens, segments = jnp.asarray(rows), jnp.asarray(segs)
for i in range(40):
    params, opt, loss = step(params, opt, tokens,
                             jax.random.PRNGKey(i), segments)
    if (i + 1) % 10 == 0:
        print(f"step {i + 1}: loss {float(loss):.4f}")
