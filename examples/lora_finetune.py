"""LoRA fine-tuning of a pretrained transformer LM.

"Pretrains" a base LM on one distribution, then adapts it to a shifted
distribution touching only rank-4 factors on wq/wv — ~1% of the
parameters — and exports the merged model for serving.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elephas_tpu.models.lora import (init_lora_params, lora_param_count,
                                     make_lora_train_step, merge_lora)
from elephas_tpu.models.transformer import (TransformerConfig, forward,
                                            init_params, lm_loss,
                                            make_train_step)

config = TransformerConfig(vocab_size=256, num_layers=2, num_heads=4,
                           d_model=64, d_ff=128, max_seq_len=32,
                           positional="rope", dtype=jnp.float32)
rng = np.random.default_rng(0)

# base task: ascending mod-256 sequences
base_data = (np.arange(32)[None, :] + rng.integers(0, 256, (128, 1))) % 256
params = init_params(config, jax.random.PRNGKey(0))
tx = optax.adam(1e-3)
opt = tx.init(params)
step = make_train_step(config, tx)
for i in range(20):
    params, opt, loss = step(params, opt, jnp.asarray(base_data))
print(f"base model loss: {float(loss):.4f}")

# adaptation task: DESCENDING sequences — fine-tune only LoRA factors
adapt_data = (rng.integers(0, 256, (128, 1)) - np.arange(32)[None, :]) % 256
lora = init_lora_params(params, config, jax.random.PRNGKey(1), rank=4)
full = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))
print(f"trainable: {lora_param_count(lora)} of {full} params "
      f"({100 * lora_param_count(lora) / full:.2f}%)")

ltx = optax.adam(5e-3)
lopt = ltx.init(lora)
lstep = make_lora_train_step(config, ltx, alpha=8.0)
before = float(lm_loss(params, jnp.asarray(adapt_data), config))
for i in range(25):
    lora, lopt, lloss = lstep(lora, lopt, params, jnp.asarray(adapt_data))
print(f"adaptation loss: {before:.4f} -> {float(lloss):.4f}")

merged = merge_lora(params, lora, config, alpha=8.0)
print("merged-model adaptation loss:",
      round(float(lm_loss(merged, jnp.asarray(adapt_data), config)), 4))
print("base model unchanged:",
      round(float(lm_loss(params, jnp.asarray(base_data), config)), 4))
