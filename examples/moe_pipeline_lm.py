"""Mixture-of-experts LM with expert parallelism, plus a pipelined stack.

Beyond-the-reference example covering the two newest parallelism axes:

1. an MoE transformer (Switch top-1 gating + load-balance aux loss) whose
   experts shard over the ``model`` mesh axis — expert parallelism, and
2. the same residual-block stack run as a GPipe-style microbatched
   pipeline over a ``pipe`` axis.

Runs on any device count (scales the mesh down gracefully).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elephas_tpu.models.transformer import (TransformerConfig, init_params,
                                            make_train_step, shard_params)
from elephas_tpu.parallel import make_pipeline_fn, stack_stage_params

# ---------------------------------------------------------- expert parallel
n = len(jax.devices())
dp = 2 if n >= 2 else 1
tp = max(d for d in (1, 2, 4) if d <= n // dp)
mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
            ("data", "model"))
print(f"mesh: data={dp} model(/expert)={tp}")

config = TransformerConfig(vocab_size=512, num_layers=2, num_heads=8,
                           d_model=128, d_ff=256, max_seq_len=128,
                           num_experts=max(tp, 2), expert_top_k=1)
params = shard_params(init_params(config, jax.random.PRNGKey(0)), config, mesh)
tx = optax.adam(3e-4)
opt_state = jax.jit(tx.init)(params)

rng = np.random.default_rng(0)
base = rng.integers(0, config.vocab_size, 128)
tokens = np.stack([np.roll(base, i) for i in range(8 * dp)]).astype(np.int32)
tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))

step = make_train_step(config, tx, mesh=mesh)
for i in range(20):
    params, opt_state, loss = step(params, opt_state, tokens)
    if i % 5 == 0:
        print(f"[moe] step {i}: loss {float(loss):.4f}")
print(f"[moe] final loss: {float(loss):.4f}")

# --------------------------------------------------------------- pipelined
pipe = max(d for d in (1, 2, 4) if d <= n)  # divisors of the batch (16)
if pipe > 1:
    pipe_mesh = Mesh(np.array(jax.devices()[:pipe]), ("pipe",))

    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w1"]) @ p["w2"]

    key = jax.random.PRNGKey(1)
    stages = []
    for s in range(pipe):
        k1, k2 = jax.random.split(jax.random.fold_in(key, s))
        stages.append({"w1": 0.3 * jax.random.normal(k1, (64, 128)),
                       "w2": 0.3 * jax.random.normal(k2, (128, 64))})
    stacked = stack_stage_params(stages)
    pipe_fn = make_pipeline_fn(stage_fn, pipe_mesh,
                               num_microbatches=pipe)
    x = jax.random.normal(jax.random.PRNGKey(2), (16, 64))
    y = jax.jit(pipe_fn)(stacked, x)
    print(f"[pipe] {pipe}-stage pipeline output: {y.shape}, "
          f"finite={bool(jnp.all(jnp.isfinite(y)))}")
