#!/usr/bin/env bash
# Run the full test suite as the CI shard matrix does: one pytest
# PROCESS per shard. Two reasons to prefer this over a single
# `pytest tests/`: (a) it is exactly what CI executes, and (b) a
# single long-lived process accumulates hundreds of tests' worth of
# jit executables, server threads, and spawned-subprocess residue —
# an XLA CPU compile deep into such a process has been observed to
# segfault (reproducibly at the same collection index, while every
# shard passes in isolation). Process-per-shard is the honest
# equivalence class.
#
#   bash run_suite.sh            # all shards, summary at the end
set -u
cd "$(dirname "$0")"
declare -a NAMES=(core ops models transformer serving engine distributed)
declare -a PATHS=(
  "tests/ml tests/mllib tests/utils tests/parameter tests/test_ps_sharding.py tests/test_ps_replication.py tests/test_matrix_model.py tests/test_model_serialization.py tests/test_tpu_callbacks.py tests/test_trainer_cache.py tests/test_ci_shards.py"
  "tests/ops"
  "tests/models --ignore=tests/models/test_transformer.py --ignore=tests/models/test_speculative.py --ignore=tests/models/test_distill.py"
  "tests/models/test_transformer.py"
  "tests/models/test_speculative.py tests/models/test_distill.py tests/test_serving.py tests/test_serving_http.py tests/test_serving_overload.py tests/test_fleet_router.py tests/test_fleet_autoscaler.py tests/test_disagg.py tests/test_prefix_cache.py tests/test_speculative_serving.py tests/test_tenant_qos.py tests/test_weightsync.py tests/test_observability.py tests/test_slo_plane.py tests/test_tracing_propagation.py tests/test_crash_safe_serving.py tests/test_network_resilience.py tests/test_kv_tiered.py tests/test_trace_plane.py tests/test_adaptive_sched.py"
  "tests/test_serving_engine.py tests/test_paged_engine.py tests/test_ssm_engine.py"
  "tests/integration tests/parallel tests/data"
)
fail=0
for i in "${!NAMES[@]}"; do
    echo "=== shard ${NAMES[$i]} ==="
    # shellcheck disable=SC2086
    if ! python -m pytest ${PATHS[$i]} -q; then
        fail=1
        echo "shard ${NAMES[$i]} FAILED"
    fi
done
[ $fail -eq 0 ] && echo "ALL SHARDS GREEN" || echo "SOME SHARD FAILED"
exit $fail
